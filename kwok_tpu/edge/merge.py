"""Strategic-merge-patch semantics for status documents + no-op suppression.

Mirrors the observable behavior of the reference's diff logic:
- configureNode (node_controller.go:356-391): render -> strategic-merge into
  current status -> **conditions excluded from the comparison** -> skip if
  equal.
- computePatchData (pod_controller.go:404-439): when phase != Pending,
  render -> strategic-merge -> skip if equal; when Pending, always patch.

Only the list merge strategies that occur in Node/Pod status are
implemented: conditions (merge key `type`), addresses (merge key `type`);
all other lists replace atomically (containerStatuses has no patch merge key
in core/v1).

`$patch: replace` / `$patch: delete` directives are honored the way the real
apiserver's strategicpatch does for these shapes: a map patch carrying
`$patch: replace` replaces the original wholesale (minus the directive);
`$patch: delete` empties it; a merge-list element `{"$patch": "delete",
<mergeKey>: v}` removes the matching element (deletes apply to the original
before the patch's own elements merge, as strategicpatch does), and a
`$patch: replace` element makes the patch's non-directive elements replace
the list. Unknown
directive values are dropped tolerantly rather than rejected
($deleteFromPrimitiveList/$setElementOrder/$retainKeys do not occur in
node/pod status traffic and are out of scope; see tests/merge_oracle.py).
"""

from __future__ import annotations

import copy
from typing import Any

# path (tuple of dict keys, "*" wildcard not needed here) -> merge key
_MERGE_KEYS: dict[str, str] = {
    "conditions": "type",
    "addresses": "type",
}

_DIRECTIVE = "$patch"


def _has_directive(item: Any) -> bool:
    return isinstance(item, dict) and _DIRECTIVE in item


def _clean(v: Any) -> bool:
    """True when a patch subtree carries no $patch markers and no nulls —
    the common case (everything the engine renders), letting insertion skip
    the sanitizing rebuild."""
    if isinstance(v, dict):
        for k, val in v.items():
            if k == _DIRECTIVE or val is None or not _clean(val):
                return False
        return True
    if isinstance(v, list):
        return all(_clean(x) for x in v)
    return True


def _sanitize(v: Any, mk: dict[str, str], field: str | None, *, copies: bool) -> Any:
    """A patch subtree being inserted where the original has no value: the
    stored object must never contain $patch markers or nulls (the real
    apiserver discards unmatched nulls — strategicpatch IgnoreUnmatchedNulls
    — and directives are instructions, not data). Equivalent to merging the
    subtree into an empty value, recursively.

    KNOWN DIVERGENCE from upstream strategicpatch removeDirectives (which
    only strips the $patch key on fresh inserts and keeps all remaining
    content): here a fresh-inserted map carrying `$patch: delete` becomes
    {} (the directive is honored against the absent original), and
    directive-carrying merge-list elements are dropped rather than kept
    marker-stripped. Deliberate tolerant behavior, mirrored by the
    independent oracle (tests/merge_oracle.py) and the C++ server
    (native/apiserver.cc sanitize_patch); engine-rendered traffic never
    contains directives, so only hand-crafted patches can observe it."""
    if _clean(v):
        return copy.deepcopy(v) if copies else v
    if isinstance(v, dict):
        if v.get(_DIRECTIVE) == "delete":
            return {}
        return {
            k: _sanitize(val, mk, k, copies=copies)
            for k, val in v.items()
            if k != _DIRECTIVE and val is not None
        }
    if isinstance(v, list) and field in mk:
        # delete/replace directives are no-ops against an empty list
        return [
            _sanitize(x, mk, None, copies=copies) for x in v if not _has_directive(x)
        ]
    return copy.deepcopy(v) if copies else v


def strategic_merge(original: Any, patch: Any, merge_keys: dict[str, str] | None = None) -> Any:
    merge_keys = _MERGE_KEYS if merge_keys is None else merge_keys
    return _merge_value(original, patch, merge_keys, field=None)


def _merge_value(
    orig: Any, patch: Any, mk: dict[str, str], field: str | None, *, copies: bool = True
) -> Any:
    """Directive-free traffic (everything the engine itself renders and
    ingests) stays on fast paths: the $patch machinery and the sanitizing
    rebuild only engage when a directive/null is actually present. This
    runs per watch event in the no-op-suppression check, so the common
    case must not pay for the rare one."""
    if isinstance(patch, dict):
        if not isinstance(orig, dict):
            return _sanitize(patch, mk, field, copies=copies)
        if _DIRECTIVE in patch:
            directive = patch[_DIRECTIVE]
            if directive == "replace":
                return {
                    k: _sanitize(v, mk, k, copies=copies)
                    for k, v in patch.items()
                    if k != _DIRECTIVE and v is not None
                }
            if directive == "delete":
                return {}
        out = dict(orig)
        for k, v in patch.items():
            if k == _DIRECTIVE:
                continue  # unknown directive value: tolerated, dropped
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = _merge_value(out[k], v, mk, field=k, copies=copies)
            else:
                out[k] = _sanitize(v, mk, k, copies=copies)
        return out
    if isinstance(patch, list):
        if isinstance(orig, list) and field in mk:
            return _merge_keyed_list(orig, patch, mk, mk[field], copies)
        # atomic-list replacement / type mismatch: sanitized like
        # missing-key insertions
        return _sanitize(patch, mk, field, copies=copies)
    return copy.deepcopy(patch) if copies else patch  # scalar leaf


def _merge_keyed_list(
    orig: list, patch: list, mk: dict[str, str], key: str, copies: bool
) -> list:
    cp = copy.deepcopy if copies else (lambda x: x)
    if any(_has_directive(it) for it in patch):
        if any(_has_directive(it) and it[_DIRECTIVE] == "replace" for it in patch):
            return [
                _sanitize(it, mk, None, copies=copies)
                for it in patch
                if not _has_directive(it)
            ]
        # strategicpatch applies every $patch:delete to the ORIGINAL before
        # merging any non-directive element, so a delete never removes an
        # element the same patch adds
        deleted = {
            it[key]
            for it in patch
            if _has_directive(it)
            and it[_DIRECTIVE] == "delete"
            and isinstance(it.get(key), str)
        }
        orig = [
            x
            for x in orig
            if not (
                isinstance(x, dict)
                and isinstance(x.get(key), str)
                and x[key] in deleted
            )
        ]
        patch = [it for it in patch if not _has_directive(it)]
    out_list = [cp(x) for x in orig] if copies else list(orig)
    # only string merge keys participate in matching (k8s merge keys are
    # always strings); first match wins on (malformed) duplicates
    index: dict[str, int] = {}
    for i, x in enumerate(out_list):
        if isinstance(x, dict):
            kv = x.get(key)
            if isinstance(kv, str) and kv not in index:
                index[kv] = i
    for item in patch:
        kv = item.get(key) if isinstance(item, dict) else None
        if isinstance(kv, str) and kv in index:
            i = index[kv]
            out_list[i] = _merge_value(out_list[i], item, mk, field=None, copies=copies)
        else:
            out_list.append(_sanitize(item, mk, None, copies=copies))
            if isinstance(kv, str):
                index[kv] = len(out_list) - 1
    return out_list


def _merge_view(orig: Any, patch: Any, mk: dict[str, str], field: str | None) -> Any:
    """strategic_merge without the defensive deepcopies: shares unmodified
    subtrees with its inputs. ONLY for read-only comparison (the no-op
    suppression checks below run once per watch event — at O(10k) events/s
    the copies dominated the engine's ingest profile). The comparisons use
    Python `==`, which unlike the former canonical-JSON compare treats
    1 == 1.0 == True — a deliberate narrowing (k8s numeric equality)."""
    return _merge_value(orig, patch, mk, field, copies=False)


def node_status_patch_needed(current_status: dict, rendered: dict) -> bool:
    """configureNode's check: merge, then compare with conditions pinned to
    the current value (node_controller.go:377 `nodeStatus.Conditions =
    node.Status.Conditions`) — heartbeat-only condition changes do not
    count as drift."""
    merged = _merge_view(current_status, rendered, _MERGE_KEYS, None)
    merged = dict(merged)
    if "conditions" in current_status:
        merged["conditions"] = current_status["conditions"]
    else:
        merged.pop("conditions", None)
    return merged != current_status


def pod_status_patch_needed(current_status: dict, rendered: dict) -> bool:
    """computePatchData's check: only suppress when phase != Pending."""
    if current_status.get("phase", "Pending") == "Pending":
        return True
    merged = _merge_view(current_status, rendered, _MERGE_KEYS, None)
    return merged != current_status
