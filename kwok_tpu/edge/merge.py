"""Strategic-merge-patch semantics for status documents + no-op suppression.

Mirrors the observable behavior of the reference's diff logic:
- configureNode (node_controller.go:356-391): render -> strategic-merge into
  current status -> **conditions excluded from the comparison** -> skip if
  equal.
- computePatchData (pod_controller.go:404-439): when phase != Pending,
  render -> strategic-merge -> skip if equal; when Pending, always patch.

Only the list merge strategies that occur in Node/Pod status are
implemented: conditions (merge key `type`), addresses (merge key `type`);
all other lists replace atomically (containerStatuses has no patch merge key
in core/v1).
"""

from __future__ import annotations

import copy
from typing import Any

# path (tuple of dict keys, "*" wildcard not needed here) -> merge key
_MERGE_KEYS: dict[str, str] = {
    "conditions": "type",
    "addresses": "type",
}


def strategic_merge(original: Any, patch: Any, merge_keys: dict[str, str] | None = None) -> Any:
    merge_keys = _MERGE_KEYS if merge_keys is None else merge_keys
    return _merge_value(original, patch, merge_keys, field=None)


def _merge_value(orig: Any, patch: Any, mk: dict[str, str], field: str | None) -> Any:
    if isinstance(patch, dict) and isinstance(orig, dict):
        out = dict(orig)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = _merge_value(out[k], v, mk, field=k)
            else:
                out[k] = copy.deepcopy(v)
        return out
    if isinstance(patch, list) and isinstance(orig, list) and field in mk:
        key = mk[field]
        out_list = [copy.deepcopy(x) for x in orig]
        index = {x.get(key): i for i, x in enumerate(out_list) if isinstance(x, dict)}
        for item in patch:
            if isinstance(item, dict) and item.get(key) in index:
                i = index[item[key]]
                out_list[i] = _merge_value(out_list[i], item, mk, field=None)
            else:
                out_list.append(copy.deepcopy(item))
        return out_list
    return copy.deepcopy(patch)


def _merge_view(orig: Any, patch: Any, mk: dict[str, str], field: str | None) -> Any:
    """strategic_merge without the defensive deepcopies: shares unmodified
    subtrees with its inputs. ONLY for read-only comparison (the no-op
    suppression checks below run once per watch event — at O(10k) events/s
    the copies dominated the engine's ingest profile). The comparisons use
    Python `==`, which unlike the former canonical-JSON compare treats
    1 == 1.0 == True — a deliberate narrowing (k8s numeric equality)."""
    if isinstance(patch, dict) and isinstance(orig, dict):
        out = dict(orig)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = _merge_view(out[k], v, mk, field=k)
            else:
                out[k] = v
        return out
    if isinstance(patch, list) and isinstance(orig, list) and field in mk:
        key = mk[field]
        out_list = list(orig)
        index = {x.get(key): i for i, x in enumerate(out_list) if isinstance(x, dict)}
        for item in patch:
            if isinstance(item, dict) and item.get(key) in index:
                i = index[item[key]]
                out_list[i] = _merge_view(out_list[i], item, mk, field=None)
            else:
                out_list.append(item)
        return out_list
    return patch


def node_status_patch_needed(current_status: dict, rendered: dict) -> bool:
    """configureNode's check: merge, then compare with conditions pinned to
    the current value (node_controller.go:377 `nodeStatus.Conditions =
    node.Status.Conditions`) — heartbeat-only condition changes do not
    count as drift."""
    merged = _merge_view(current_status, rendered, _MERGE_KEYS, None)
    merged = dict(merged)
    if "conditions" in current_status:
        merged["conditions"] = current_status["conditions"]
    else:
        merged.pop("conditions", None)
    return merged != current_status


def pod_status_patch_needed(current_status: dict, rendered: dict) -> bool:
    """computePatchData's check: only suppress when phase != Pending."""
    if current_status.get("phase", "Pending") == "Pending":
        return True
    merged = _merge_view(current_status, rendered, _MERGE_KEYS, None)
    return merged != current_status
