"""HttpKubeClient: the KubeClient protocol over a real kube-apiserver.

Replaces the reference's client-go usage: paged LIST (pager.New,
node_controller.go:282), streaming WATCH with resourceVersion resume,
strategic-merge PATCH of /status (PatchStatus, node_controller.go:345),
JSON merge-patch of metadata (removeFinalizers, pod_controller.go:45), and
grace-0 DELETE. Auth comes from a kubeconfig file or in-cluster
serviceaccount files (pkg/kwok/cmd/root.go:222-236 newClientset).
"""

from __future__ import annotations

import atexit
import base64
import http.client
import io
import json
import logging
import os
import re
import socket
import ssl
import tempfile
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator

import yaml

from kwok_tpu.edge.kubeclient import (
    ContinueExpired,
    TooLargeResourceVersion,
    TooManyRequests,
    WatchEvent,
)
from kwok_tpu.telemetry.errors import swallowed, wire_reject

logger = logging.getLogger("kwok_tpu.edge.http")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
LIST_PAGE_SIZE = 500


def _b64_to_tmp(data: str, suffix: str) -> str:
    f = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    f.write(base64.b64decode(data))
    f.close()
    # key material must not outlive the process
    atexit.register(_unlink_quiet, f.name)
    return f.name


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class HttpKubeClient:
    def __init__(
        self,
        server: str,
        *,
        token: str | None = None,
        ca_file: str | None = None,
        cert_file: str | None = None,
        key_file: str | None = None,
        insecure_skip_tls_verify: bool = False,
        timeout: float = 30.0,
    ) -> None:
        self.server = server.rstrip("/")
        self.token = token
        self.timeout = timeout
        # extra request headers applied to every unary request: the HA
        # plane plants its fencing claim here (resilience/ha.py
        # FENCE_HEADER) so the servers can reject writes from a deposed
        # holder at processing time. Empty dict = zero per-request cost
        # beyond one truthiness test.
        self.extra_headers: dict[str, str] = {}
        # per-thread persistent connections for unary requests (keep-alive):
        # a new TCP (+TLS) handshake per status patch would dominate the
        # egress at high transition rates (SURVEY.md "Hard parts":
        # connection pooling on the watch/patch edge)
        self._local = threading.local()
        split = urllib.parse.urlsplit(self.server)
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port
        # server URLs may carry a base path (proxy-style clusters); unary
        # requests must keep it when extracting the path from a full URL
        self._base_path = split.path.rstrip("/")
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        ctx: ssl.SSLContext | None = None
        if self.server.startswith("https"):
            ctx = ssl.create_default_context(cafile=ca_file)
            if insecure_skip_tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if cert_file and key_file:
                ctx.load_cert_chain(cert_file, key_file)
        self._ctx = ctx

    # ---------------------------------------------------------- construction

    @classmethod
    def from_kubeconfig(
        cls, path: str | None = None, master: str | None = None
    ) -> "HttpKubeClient":
        """Load the current-context cluster+user from a kubeconfig; fall back
        to in-cluster serviceaccount; `master` overrides the server URL."""
        path = path or os.environ.get("KUBECONFIG") or os.path.expanduser(
            "~/.kube/config"
        )
        if os.path.exists(path):
            with open(path) as f:
                cfg = yaml.safe_load(f) or {}
            ctx_name = cfg.get("current-context")
            contexts = {c["name"]: c["context"] for c in cfg.get("contexts") or []}
            clusters = {c["name"]: c["cluster"] for c in cfg.get("clusters") or []}
            users = {u["name"]: u["user"] for u in cfg.get("users") or []}
            ctx = contexts.get(ctx_name) or (next(iter(contexts.values()), {}))
            cluster = clusters.get(ctx.get("cluster"), {}) if ctx else {}
            user = users.get(ctx.get("user"), {}) if ctx else {}
            ca = cluster.get("certificate-authority")
            if not ca and cluster.get("certificate-authority-data"):
                ca = _b64_to_tmp(cluster["certificate-authority-data"], ".crt")
            cert = user.get("client-certificate")
            if not cert and user.get("client-certificate-data"):
                cert = _b64_to_tmp(user["client-certificate-data"], ".crt")
            key = user.get("client-key")
            if not key and user.get("client-key-data"):
                key = _b64_to_tmp(user["client-key-data"], ".key")
            return cls(
                master or cluster.get("server") or "http://127.0.0.1:8080",
                token=user.get("token"),
                ca_file=ca,
                cert_file=cert,
                key_file=key,
                insecure_skip_tls_verify=bool(
                    cluster.get("insecure-skip-tls-verify")
                ),
            )
        if master:
            return cls(master)
        # in-cluster (root.go: rest.InClusterConfig path)
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if host:
            token = ""
            token_file = os.path.join(_SA_DIR, "token")
            if os.path.exists(token_file):
                token = open(token_file).read().strip()
            return cls(
                f"https://{host}:{port}",
                token=token or None,
                ca_file=os.path.join(_SA_DIR, "ca.crt"),
            )
        raise RuntimeError("no kubeconfig, --master, or in-cluster environment")

    # -------------------------------------------------------------- plumbing

    _RBAC_KINDS = frozenset(
        {"roles", "rolebindings", "clusterroles", "clusterrolebindings"}
    )

    def _url(self, kind: str, namespace: str | None = None, name: str | None = None,
             subresource: str | None = None, query: dict | None = None) -> str:
        parts = [
            "/apis/rbac.authorization.k8s.io/v1"
            if kind in self._RBAC_KINDS
            else "/api/v1"
        ]
        if namespace:
            parts.append(f"/namespaces/{namespace}")
        parts.append(f"/{kind}")
        if name:
            parts.append(f"/{name}")
        if subresource:
            parts.append(f"/{subresource}")
        url = self.server + "".join(parts)
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v not in (None, "")}
            )
        return url

    def _request(self, method: str, url: str, body: bytes | None = None,
                 content_type: str | None = None, timeout: float | None = None):
        req = urllib.request.Request(url, data=body, method=method)
        if content_type:
            req.add_header("Content-Type", content_type)
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(
            req, context=self._ctx, timeout=timeout or self.timeout
        )

    def _conn(self):
        c = getattr(self._local, "conn", None)
        if c is None:
            if self.server.startswith("https"):
                c = http.client.HTTPSConnection(
                    self._host, self._port, context=self._ctx,
                    timeout=self.timeout,
                )
            else:
                c = http.client.HTTPConnection(
                    self._host, self._port, timeout=self.timeout
                )
            c.connect()
            try:
                # Without this, request bodies Nagle-stall behind the
                # server's delayed ACK on every keep-alive round trip.
                c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except (OSError, AttributeError):
                pass
            self._local.conn = c
            with self._conns_lock:
                self._conns.add(c)
        return c

    def close(self) -> None:
        """Close every pooled keep-alive connection (all threads)."""
        with self._conns_lock:
            conns, self._conns = self._conns, set()
        for c in conns:
            try:
                c.close()
            except Exception:
                # best-effort teardown of a possibly-dead keep-alive
                swallowed("httpclient.pool_close")
        self._local = threading.local()

    def _json(self, method: str, url: str, body: dict | bytes | None = None,
              content_type: str = "application/json") -> dict | None:
        # bytes-like bodies are pre-encoded JSON (native codec egress)
        if isinstance(body, (bytes, bytearray, memoryview)):
            data = bytes(body)
        else:
            data = json.dumps(body).encode() if body is not None else None
        path = (self._base_path + url[len(self.server):]) or "/"
        headers = {"Accept": "application/json"}
        if data is not None and content_type:
            headers["Content-Type"] = content_type
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.extra_headers:
            headers.update(self.extra_headers)
        for attempt in (0, 1):
            conn = None
            try:
                conn = self._conn()
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                status = resp.status
                break
            except (http.client.HTTPException, OSError):
                # stale keep-alive connection; rebuild once, then give up
                try:
                    conn.close()
                except Exception:
                    swallowed("httpclient.stale_conn_close")
                self._local.conn = None
                if attempt:
                    raise
        if status == 404:
            return None
        if status == 429:
            # a max-inflight band is saturated: typed so callers throttle
            # by the server's Retry-After hint (never a blind hot retry)
            try:
                ra = float(resp.getheader("Retry-After") or 1)
            except ValueError:
                ra = 1.0
            raise TooManyRequests(
                payload.decode(errors="replace"), retry_after=ra
            )
        if status >= 400:
            raise urllib.error.HTTPError(
                url, status, payload.decode(errors="replace"), None, None
            )
        try:
            return json.loads(payload or b"null")
        except ValueError:
            # a 2xx response whose body does not decode: garbled or
            # truncated on the wire. Counted, then raised — every caller
            # (watch loop, patch executor) already treats this as a
            # transient failure and re-fetches, which is the repair.
            wire_reject("http_body")
            raise

    # ------------------------------------------------------------- KubeClient

    def list(self, kind, *, field_selector=None, label_selector=None) -> list[dict]:
        items: list[dict] = []
        cont = None
        while True:
            try:
                doc = self._json(
                    "GET",
                    self._url(kind, query={
                        "fieldSelector": field_selector,
                        "labelSelector": label_selector,
                        "limit": LIST_PAGE_SIZE,
                        "continue": cont,
                    }),
                ) or {}
            except urllib.error.HTTPError as e:
                if e.code == 410 and cont:
                    # continue token compacted away mid-pagination:
                    # restart the list from scratch (client-go pager's
                    # fallback on Expired)
                    logger.warning(
                        "list %s continue token expired; restarting", kind
                    )
                    items.clear()
                    cont = None
                    continue
                raise
            for item in doc.get("items") or []:
                item.setdefault("apiVersion", "v1")
                items.append(item)
            cont = (doc.get("metadata") or {}).get("continue")
            if not cont:
                return items

    def list_page(self, kind, *, limit: int, cont: str = "",
                  field_selector=None, label_selector=None):
        """ONE page of a paged LIST — the anti-entropy auditor's budgeted
        read primitive (resilience/antientropy.py): the auditor bounds
        pages per pass so it can never self-inflict a 429 storm, and
        resumes the continue cursor on its next pass. Returns
        ``(items, continue_token)``; an expired cursor (410 mid-scan)
        raises typed :class:`ContinueExpired` — a caller must restart
        its scan, and must NOT mistake the expiry for a completed one
        (a legitimately-empty final page also returns no token)."""
        try:
            doc = self._json(
                "GET",
                self._url(kind, query={
                    "fieldSelector": field_selector,
                    "labelSelector": label_selector,
                    "limit": limit,
                    "continue": cont or None,
                }),
            ) or {}
        except urllib.error.HTTPError as e:
            if e.code == 410 and cont:
                logger.warning(
                    "audit list %s continue token expired; restarting scan",
                    kind,
                )
                raise ContinueExpired(kind) from e
            raise
        items = []
        for item in doc.get("items") or []:
            item.setdefault("apiVersion", "v1")
            items.append(item)
        return items, (doc.get("metadata") or {}).get("continue") or ""

    def watch(self, kind, *, field_selector=None, label_selector=None,
              resource_version=None, allow_bookmarks=False):
        return _HttpWatch(
            self, kind, field_selector, label_selector, resource_version,
            allow_bookmarks,
        )

    def get(self, kind, namespace, name):
        return self._json("GET", self._url(kind, namespace, name))

    def create(self, kind, obj, namespace=None):
        """POST a new object (used by load rigs and tests; the engine itself
        never creates API objects)."""
        ns = namespace or (obj.get("metadata") or {}).get("namespace")
        return self._json("POST", self._url(kind, ns), obj)

    def patch_status(self, kind, namespace, name, patch):
        return self._json(
            "PATCH",
            self._url(kind, namespace, name, "status"),
            patch,
            "application/strategic-merge-patch+json",
        )

    def patch_meta(self, kind, namespace, name, patch):
        return self._json(
            "PATCH",
            self._url(kind, namespace, name),
            patch,
            "application/merge-patch+json",
        )

    def delete(self, kind, namespace, name, grace_seconds: int | None = 0):
        """grace_seconds=None omits DeleteOptions.gracePeriodSeconds so the
        server applies its default (pods: spec.terminationGracePeriodSeconds
        or 30, like the real apiserver)."""
        self._json(
            "DELETE",
            self._url(kind, namespace, name),
            None if grace_seconds is None else {"gracePeriodSeconds": grace_seconds},
        )

    def bind(self, namespace, name, node: str):
        """POST pods/NAME/binding — the kube-scheduler's bind call."""
        return self._json(
            "POST",
            self._url("pods", namespace, name, subresource="binding"),
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": name, "namespace": namespace},
                "target": {"apiVersion": "v1", "kind": "Node", "name": node},
            },
        )

    # ------------------------------------------- coordination.k8s.io leases

    def _lease_url(self, namespace: str, name: str | None = None) -> str:
        url = (
            f"{self.server}/apis/coordination.k8s.io/v1/namespaces/"
            f"{namespace}/leases"
        )
        return url + (f"/{name}" if name else "")

    def _lease_call(self, method, url, body=None,
                    content_type="application/json"):
        """One lease op -> ``(status_code, parsed_doc | None)``. Unlike
        the resource verbs, lease denials (409 Conflict / AlreadyExists)
        are NORMAL protocol answers the elector switches on every poll —
        surfacing them as exceptions would make the common path the
        exceptional one. Transport failures still raise."""
        try:
            doc = self._json(method, url, body, content_type)
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(str(e.reason) or "null")
            except ValueError:
                doc = None
            return e.code, doc
        if doc is None:
            return 404, None
        return (201 if method == "POST" else 200), doc

    def lease_get(self, namespace, name):
        """GET the Lease -> (code, doc); 404 means it does not exist."""
        return self._lease_call("GET", self._lease_url(namespace, name))

    def lease_create(self, namespace, name, spec):
        """POST a fresh Lease (first acquisition; leaseTransitions starts
        at 0) -> (201, doc) or (409, Status) when it already exists."""
        return self._lease_call(
            "POST", self._lease_url(namespace),
            {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": name, "namespace": namespace},
                "spec": dict(spec or {}),
            },
        )

    def lease_renew(self, namespace, name, spec):
        """PATCH-renew/acquire -> (200, doc), (409, Status) while someone
        else holds it unexpired, or (404, None) when absent."""
        return self._lease_call(
            "PATCH", self._lease_url(namespace, name),
            {"spec": dict(spec or {})},
            "application/merge-patch+json",
        )

    def healthz(self) -> bool:
        try:
            with self._request("GET", self.server + "/healthz") as resp:
                return resp.status == 200
        except Exception:
            # probe contract: unreachable == unhealthy, but leave a trace
            logger.debug("healthz probe failed", exc_info=True)
            return False


class _HttpWatch:
    """One streaming watch connection; iterating yields WatchEvents until the
    server closes the stream or stop() is called. The engine's watch loop
    handles reconnect+resync."""

    def __init__(self, client: HttpKubeClient, kind: str, field_selector,
                 label_selector, resource_version=None,
                 allow_bookmarks=False):
        self.client = client
        self._stopped = threading.Event()
        #: set when the stream ended with an ERROR event carrying a 410
        #: Status — the resume revision was compacted; caller must re-list
        self.expired = False
        url = client._url(kind, query={
            "watch": "true",
            "fieldSelector": field_selector,
            "labelSelector": label_selector,
            "resourceVersion": (
                str(resource_version) if resource_version else None
            ),
            "allowWatchBookmarks": (
                "true" if allow_bookmarks else "false"
            ),
        })
        # no read timeout: watch connections idle legitimately
        try:
            self._resp = client._request("GET", url, timeout=3600.0)
        except urllib.error.HTTPError as e:
            if e.code == 429:
                # watch handshake rejected by a saturated max-inflight
                # band: typed, so the reconnect loop throttles by the
                # server's hint instead of hammering the handshake
                try:
                    ra = float(
                        (e.headers.get("Retry-After") if e.headers else None)
                        or 1
                    )
                except ValueError:
                    ra = 1.0
                body = e.read() if hasattr(e, "read") else b""
                raise TooManyRequests(
                    body.decode(errors="replace"), retry_after=ra
                ) from e
            # a resume AHEAD of the server's store fails the watch
            # handshake with 504 + a ResourceVersionTooLarge cause
            # (storage.NewTooLargeResourceVersionError); surface it typed
            # so the engine can retry-with-hint instead of re-listing
            if e.code == 504:
                body = e.read() if hasattr(e, "read") else b""
                try:
                    doc = json.loads(body or (e.reason or "{}"))
                except (json.JSONDecodeError, TypeError):
                    doc = {}
                details = doc.get("details") or {}
                causes = details.get("causes") or []
                if any(
                    c.get("reason") == "ResourceVersionTooLarge"
                    for c in causes
                ):
                    # the server's current revision rides in the message
                    # ("Too large resource version: X, current: Y")
                    m = re.search(
                        r"current: (\d+)", doc.get("message") or ""
                    )
                    raise TooLargeResourceVersion(
                        int(resource_version or 0),
                        int(m.group(1)) if m else 0,
                        float(details.get("retryAfterSeconds") or 1),
                    ) from e
                # sniffing consumed the body; re-raise a generic 504 with
                # the Status JSON re-attached so callers can still read
                # the API's documented error shape (HTTPError.read binds
                # the ORIGINAL fp — a fresh error is the only way back)
                raise urllib.error.HTTPError(
                    e.url, e.code, e.reason, e.headers, io.BytesIO(body)
                ) from e
            raise

    def __iter__(self) -> Iterator[WatchEvent]:
        try:
            for raw in self._resp:
                if self._stopped.is_set():
                    return
                line = raw.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:  # JSONDecodeError or bad UTF-8
                    # corrupt bytes on the watch stream: integrity doubt.
                    # Skipping would silently lose whatever event the line
                    # carried (its rv is unreadable, so nothing would ever
                    # re-deliver it); ending the stream makes the engine's
                    # reconnect resume from the last good revision — the
                    # server replays the gap, the echo-drop absorbs the
                    # duplicates, and the corrupt event comes back whole.
                    wire_reject("watch_line")
                    logger.warning(
                        "bad watch line (ending stream for resume): "
                        "%.120r", line,
                    )
                    return
                type_ = doc.get("type")
                if type_ in ("ADDED", "MODIFIED", "DELETED", "BOOKMARK"):
                    # BOOKMARK objects carry only metadata.resourceVersion;
                    # callers advance their resume revision and move on
                    yield WatchEvent(type_, doc.get("object") or {})
                elif type_ == "ERROR":
                    obj = doc.get("object") or {}
                    if obj.get("code") == 410:
                        self.expired = True
                    logger.warning("watch error event: %s", obj)
                    return
        finally:
            try:
                self._resp.close()
            except Exception:
                # a stopped stream may already be torn down (shutdown race)
                swallowed("httpclient.watch_close")

    def native_reader(self):
        """Hand the stream to the native batched line reader (ingest.cc
        watch IO) AFTER the Python HTTP handshake: plain-HTTP responses
        backed by a real socket only. Returns a native.WatchReader (its
        read_batch() yields packed line batches for
        EventParser.parse_blob) or None — callers fall back to
        raw_lines(). Bytes http.client already read ahead are drained
        from its buffer non-blockingly and handed over, so the reader
        starts exactly where the handshake left off."""
        if os.environ.get("KWOK_TPU_NATIVE_WATCH", "1") == "0":
            return None
        try:
            from kwok_tpu import native
        except ImportError:
            return None
        if not native.available():
            return None
        resp = self._resp
        try:
            fp = resp.fp
            sock = fp.raw._sock  # http.client internals (same as stop())
            if not isinstance(sock, socket.socket) or isinstance(
                sock, ssl.SSLSocket
            ):
                return None  # TLS bytes are not readable off the raw fd
            chunked = bool(getattr(resp, "chunked", False))
            sock.setblocking(False)
            buffered = b""
            try:
                while True:
                    try:
                        part = fp.read1(1 << 20)
                    except (BlockingIOError, ssl.SSLWantReadError):
                        break
                    if not part:
                        break
                    buffered += part
            finally:
                sock.setblocking(True)
            return native.WatchReader(sock.fileno(), buffered, chunked)
        except Exception:
            logger.debug("native watch reader unavailable", exc_info=True)
            return None

    def raw_lines(self) -> Iterator[bytes]:
        """Undecoded event lines — the engine's native ingest parses them in
        C++ (kwok_tpu.native.EventParser) instead of json.loads per event."""
        try:
            for raw in self._resp:
                if self._stopped.is_set():
                    return
                line = raw.strip()
                if line:
                    yield line
        finally:
            try:
                self._resp.close()
            except Exception:
                swallowed("httpclient.watch_close")

    def stop(self) -> None:
        self._stopped.set()
        # Closing the response would block on the buffer lock held by a
        # reader mid-readline; shutting the socket down unblocks the reader
        # with EOF instead.
        try:
            sock = self._resp.fp.raw._sock  # http.client internals
            sock.shutdown(socket.SHUT_RDWR)
        except Exception:
            try:
                self._resp.close()
            except Exception:
                swallowed("httpclient.watch_stop")
