"""In-memory mock kube-apiserver (library + standalone process).

Two layers:
- FakeKube: object store with resourceVersion bumps, watch fan-out,
  strategic-merge status patches and kubelet-style graceful deletion --
  the in-process analogue of client-go's fake clientset
  (node_controller_test.go:38, pod_controller_test.go:38-71).
- HttpFakeApiserver: an HTTP facade speaking the kube-apiserver wire
  protocol (list/watch/get/patch/delete on /api/v1 paths, chunked watch
  streams, /healthz) over real sockets.

Used by the test suite and by the kwokctl `mock` runtime, whose generated
kube-apiserver shim runs main() as a detached process in air-gapped
environments where real control-plane binaries cannot be downloaded.
"""

from __future__ import annotations

import base64
import binascii
import collections
import copy
import json
import os
import queue
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterator

from kwok_tpu.telemetry.apiserver_metrics import (
    ApiserverTiming,
    LagHist,
    render_apiserver_metrics,
    render_timing_metrics,
)
from kwok_tpu.telemetry.errors import swallowed
from kwok_tpu.edge.kubeclient import (
    ADDED,
    BOOKMARK,
    DELETED,
    MODIFIED,
    TooLargeResourceVersion,
    WatchEvent,
    WatchExpired,
    match_field_selector,
)
from kwok_tpu.edge.merge import strategic_merge
from kwok_tpu.edge.render import now_rfc3339
from kwok_tpu.edge.selectors import parse_selector


class BindConflict(Exception):
    """pods/binding on an already-bound pod (HTTP 409)."""


class MalformedContinue(Exception):
    """An undecodable list continue token (HTTP 400, like the real
    apiserver's "continue key is not valid"; distinct from the 410 an
    EXPIRED token gets)."""


class AlreadyExists(Exception):
    """POST of an explicitly named object whose name is taken (HTTP 409,
    reason AlreadyExists — the real apiserver never overwrites on create;
    generateName collisions are retried server-side instead)."""


class _BadBody(Exception):
    """A request body that does not decode as JSON — garbled or truncated
    on the wire. Answered 400 with the same Status body the C++ mirror
    sends (parity-pinned), never a handler crash: hostile request bytes
    must not kill the connection thread or wedge the store lock."""


class _RingEv:
    """One serialize-once broadcast-ring entry (ISSUE 13): the event line
    is encoded exactly once at emit time and SHARED by every watcher whose
    cursor passes it. ``line`` is the full wire event
    (``{"type":T,"object":O}\\n`` — byte-identical to what json.dumps
    produced when each watcher encoded its own copy); ``doc`` is the
    lazily-parsed document, materialized only for selector matching and
    in-process consumers (plain HTTP watchers never pay a parse)."""

    __slots__ = ("kind", "type", "line", "bookmark", "_doc")

    def __init__(self, kind: str, type_: str, line: bytes,
                 bookmark: bool = False):
        self.kind = kind
        self.type = type_
        self.line = line
        self.bookmark = bookmark
        self._doc = None

    def obj(self) -> dict:
        if self._doc is None:
            self._doc = json.loads(self.line)
        return self._doc["object"]


class _CompatQueue:
    """queue.Queue-shaped view over a cursor watch (tests and in-process
    consumers use ``w.q.get_nowait()`` / ``qsize()``; the HTTP facade and
    the iterator read the ring directly). Only MATCHING events count —
    the same events the old per-watcher queue would have held."""

    def __init__(self, w: "_Watch"):
        self._w = w

    def get(self, block: bool = True, timeout: "float | None" = None):
        ev = self._w._next_event(block=block, timeout=timeout)
        if ev is _STOPPED:
            return None  # the old stop sentinel
        if ev is None:
            raise queue.Empty
        return ev

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return self._w._pending_count()

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item) -> None:  # pragma: no cover - legacy shim
        raise TypeError("ring watches are server-fed; use the store API")


_STOPPED = object()  # sentinel: the watch ended (old q's None)


class _Watch:
    """A cursor into the store's broadcast ring (ISSUE 13). The server
    encodes each watch event ONCE into the shared ring; every watch holds
    ``cursor`` (the next ring sequence it will read) plus a private,
    cap-exempt ``replay`` of resume-gap events from the watch cache. A
    watch whose cursor falls more than ``watch_backlog`` events behind the
    ring head is closed with ``terminated="slow"`` — PR 8's bounded-
    backlog semantics folded into ring-cursor lag."""

    def __init__(self, server: "FakeKube", kind: str, field_selector, label_selector):
        self.server = server
        self.kind = kind
        self.field_selector = field_selector
        self.label_selector = parse_selector(label_selector)
        #: next ring sequence to read (guarded by the store's _ring_lock)
        self.cursor = 0
        #: events delivered before ``stop_seq`` even after a graceful stop
        self.stop_seq = None
        #: resume replay from the watch cache: (type, object-bytes) pairs,
        #: exempt from the lag cap (bounded by RV_WINDOW already)
        self.replay: "collections.deque" = collections.deque()
        self.stopped = False
        #: opted into periodic BOOKMARK events (allowWatchBookmarks=true)
        self.bookmarks = False
        #: set to the reason ("slow") when the SERVER closed this watch
        #: because its ring-cursor lag exceeded the backlog cap — the HTTP
        #: facade closes the connection abruptly instead of letting a
        #: consumer that stopped reading pin unbounded memory
        self.terminated: "str | None" = None
        #: wall stamp of registration — GET /debug/watchers age_s
        self.created_unix = time.time()
        self.q = _CompatQueue(self)

    def _matches(self, obj: dict) -> bool:
        if not match_field_selector(obj, self.field_selector):
            return False
        if self.label_selector is not None:
            labels = (obj.get("metadata") or {}).get("labels") or {}
            if not self.label_selector.matches(labels):
                return False
        return True

    def _takes(self, ev: _RingEv) -> bool:
        """Whether this watch consumes a ring event (kind + bookmark
        opt-in + selectors); runs on the WATCHER's thread, not the
        writer's — the per-watcher filter cost left the commit path."""
        if ev.kind != self.kind:
            return False
        if ev.bookmark:
            return self.bookmarks
        if self.field_selector is None and self.label_selector is None:
            return True
        return self._matches(ev.obj())

    # ---- delivery (all ring reads under the store's _ring_lock) --------

    def _scan_locked(self):
        """Advance the cursor to the next matching ring event and return
        it, or None when drained (caller holds _ring_lock)."""
        s = self.server
        while True:
            if self.replay:
                return self.replay.popleft()
            limit = s._ring_next
            if self.stop_seq is not None:
                limit = min(limit, self.stop_seq)
            if self.cursor >= limit:
                return None
            base = s._ring_next - len(s._ring)
            if self.cursor < base:
                # trimmed past us (stopped watch): nothing left to read
                self.cursor = base
                continue
            ev = s._ring[self.cursor - base]
            self.cursor += 1
            if self._takes(ev):
                return ev

    def _next_event(self, block: bool = True, timeout: "float | None" = None):
        """Next matching WatchEvent, ``_STOPPED`` when the stream ended,
        or None on timeout/empty (non-blocking)."""
        s = self.server
        deadline = None if timeout is None else time.monotonic() + timeout
        with s._ring_lock:
            while True:
                ev = self._scan_locked()
                if ev is not None:
                    return WatchEvent(ev.type, ev.obj())
                if self.stopped:
                    return _STOPPED
                if not block:
                    return None
                if deadline is None:
                    s._ring_cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not s._ring_cond.wait(remaining):
                        if deadline - time.monotonic() <= 0:
                            return None

    def _pending_count(self) -> int:
        """Matching events between cursor and head (non-consuming)."""
        s = self.server
        with s._ring_lock:
            n = len(self.replay)
            base = s._ring_next - len(s._ring)
            limit = s._ring_next
            if self.stop_seq is not None:
                limit = min(limit, self.stop_seq)
            for seq in range(max(self.cursor, base), limit):
                if self._takes(s._ring[seq - base]):
                    n += 1
            return n

    def take_lines(self, timeout: "float | None" = None):
        """HTTP stream writer: block for the next batch of matching
        event LINES (shared bytes, one chunk each). Returns
        ``(lines, state)`` where state is "ok", "stopped" (close the
        stream) or "timeout" (deadline slice elapsed, nothing pending)."""
        s = self.server
        deadline = None if timeout is None else time.monotonic() + timeout
        with s._ring_lock:
            while True:
                # the deadline closes at the next event BOUNDARY past it,
                # pending backlog or not (a flooding stream must not be
                # able to outrun its own timeoutSeconds)
                if deadline is not None and time.monotonic() >= deadline:
                    return [], "timeout"
                lines = []
                take = 0
                while take < 4 << 20:
                    ev = self._scan_locked()
                    if ev is None:
                        break
                    lines.append(ev.line)
                    take += len(ev.line)
                if lines:
                    return lines, "ok"
                if self.stopped:
                    return [], "stopped"
                if deadline is None:
                    s._ring_cond.wait()
                else:
                    s._ring_cond.wait(
                        max(0.0, deadline - time.monotonic())
                    )

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            ev = self._next_event()
            if ev is _STOPPED:
                return
            yield ev

    def stop(self) -> None:
        s = self.server
        with s._ring_lock:
            # route through the server's close so the per-kind live
            # count drops (a leaked count would keep the ring encoding
            # for kinds nobody watches and inflate fanout_total forever)
            s._close_watch_locked(self)
            s._ring_cond.notify_all()


def _event_line(type_: str, data: bytes) -> bytes:
    """The serialized watch event line, built from the object's cached
    bytes — byte-identical to
    ``json.dumps({"type": type_, "object": obj}, separators=(",", ":"))``
    plus the newline, without re-serializing the object."""
    return b'{"type":"' + type_.encode() + b'","object":' + data + b"}\n"


# core/v1 kinds plus the rbac.authorization.k8s.io/v1 group served when the
# cluster runs with --kube-authorization (reference: kube-apiserver
# --authorization-mode=Node,RBAC, components/kube_apiserver.go:78-151).
# "events" exists so a real kube-scheduler's event POSTs land instead of
# 404ing (the mock is the stand-in for the real apiserver the reference's
# e2e drives a real scheduler against).
KINDS = (
    "nodes",
    "pods",
    "roles",
    "rolebindings",
    "clusterroles",
    "clusterrolebindings",
    "events",
)

# the real apiserver expires events on a ~1h etcd lease (kube-apiserver
# --event-ttl, re-leased on every write); the mock bounds the store by count
# instead (least-recently-written evicted on insert) so long soaks with a
# real scheduler can't grow it without bound. Overridable for tests;
# <= 0 means unbounded.
EVENTS_CAP = int(os.environ.get("KWOK_TPU_EVENTS_CAP", "4096"))

# watch-cache window: how many recent events are retained for
# resourceVersion-resumed watches. Resuming below the window gets the real
# apiserver's 410 Gone ("too old resource version", etcd compaction
# semantics); <= 0 disables the cache so every resume expires. Mirrored by
# apiserver.cc; same env override.
RV_WINDOW = int(os.environ.get("KWOK_TPU_RV_WINDOW", "4096"))

# bounded per-watcher send buffer: a consumer that stops reading has its
# watch TERMINATED (connection closed at the current event boundary,
# kwok_watch_terminations_total{reason="slow"}) once this many events are
# queued, instead of growing the queue without bound — the watch cache's
# slow-consumer termination; the client recovers by resuming/re-listing
# (the same expiry-class path a 410 takes). <= 0 disables the cap.
# Mirrored by apiserver.cc; same env override. The resume replay a fresh
# watch receives is exempt (it is bounded by RV_WINDOW already — capping
# it would terminate every resume whose gap exceeds the backlog, a loop).
WATCH_BACKLOG = int(os.environ.get("KWOK_TPU_WATCH_BACKLOG", "16384"))

# Two-band max-inflight admission (kube-apiserver --max-requests-inflight /
# --max-mutating-requests-inflight, KEP-1040's predecessor knobs): when a
# band is saturated the server answers 429 + Retry-After instead of
# queueing unboundedly. 0 disables a band (the default: zero admission
# cost when unconfigured). Watches are long-running and exempt, like the
# real apiserver's longRunningRequestCheck; they are bounded by
# WATCH_BACKLOG instead. Mirrored by apiserver.cc; same env overrides.
MAX_INFLIGHT = int(os.environ.get("KWOK_TPU_MAX_INFLIGHT", "0"))
MAX_MUTATING_INFLIGHT = int(
    os.environ.get("KWOK_TPU_MAX_MUTATING_INFLIGHT", "0")
)

# The 429 dialect, byte-identical across both servers (parity-pinned):
# kube-apiserver's TooManyRequests Status plus a Retry-After hint the
# client's RetryPolicy must honor (throttle, never hammer).
RETRY_AFTER_SECONDS = "1"
TOO_MANY_REQUESTS_BODY = (
    b'{"kind":"Status","apiVersion":"v1","status":"Failure",'
    b'"message":"Too many requests, please try again later.",'
    b'"reason":"TooManyRequests","code":429}'
)

# BOOKMARK cadence for opted-in watches (allowWatchBookmarks=true): a
# periodic event carrying only metadata.resourceVersion so a QUIET watch's
# resume revision keeps advancing and compaction can't strand it into a
# 410 + full re-list (client-go reflector's bookmark purpose; the real
# apiserver's watch cache sends them roughly every minute). <= 0 disables
# the timer; tests drive emit_bookmarks() directly. Mirrored by
# apiserver.cc; same env override.
BOOKMARK_INTERVAL = float(os.environ.get("KWOK_TPU_BOOKMARK_INTERVAL", "60"))

#: plural resource -> object kind, for bookmark objects and snapshots
KIND_SINGULAR = {
    "nodes": "Node",
    "pods": "Pod",
    "roles": "Role",
    "rolebindings": "RoleBinding",
    "clusterroles": "ClusterRole",
    "clusterrolebindings": "ClusterRoleBinding",
    "events": "Event",
}


class _Shard:
    """One (kind, namespace) store partition (ISSUE 13): its own RLock,
    live objects and the per-object serialized-bytes cache. Writers to
    different shards no longer serialize on one index; the global event
    order (revision allocation + ring/history append) is the ONLY shared
    critical section, taken under the store's ``_ring_lock`` while the
    shard lock is held (a declared 87 → 88 descent, see
    docs/static-analysis.md). Shard locks never nest with each other —
    cross-shard reads (LIST/snapshot) visit shards sequentially and
    reconcile through the undo log instead."""

    __slots__ = ("_shard_lock", "objs", "json")

    def __init__(self) -> None:
        self._shard_lock = threading.RLock()
        self.objs: dict[str, dict] = {}   # name -> live object
        self.json: dict[str, bytes] = {}  # name -> serialized bytes


class FakeKube:
    """kinds: "nodes"/"clusterroles"/"clusterrolebindings" (cluster-scoped),
    "pods"/"roles"/"rolebindings" (namespaced). Sharded by (kind,
    namespace) with a serialize-once broadcast ring for watch fanout
    (ISSUE 13); the C++ twin (native/apiserver.cc) mirrors the design."""

    def __init__(self) -> None:
        # shard registry: kind -> ns -> _Shard. The registry dict itself
        # is swapped atomically on restore; _shard_idx_lock guards only
        # shard creation and is never held with any other lock.
        self._shard_idx_lock = threading.Lock()
        self._shards: dict[str, dict[str, _Shard]] = {k: {} for k in KINDS}
        # the ring/clock lock: revision allocation, watch cache (history),
        # undo log, broadcast ring, watch registry, per-kind counts. The
        # condition shares the lock so commit can notify watchers inline.
        self._ring_lock = threading.RLock()
        self._ring_cond = threading.Condition(self._ring_lock)
        self._rv = 0
        self._watches: list[_Watch] = []
        #: live watch count per kind: events are encoded into the ring
        #: only when someone could consume them
        self._kind_watchers: dict[str, int] = {}
        # watch cache: recent (rv, kind, type, bytes) for resumed watches;
        # everything at or below _compacted_rv has been compacted away
        # (resume -> 410 Gone, like etcd compaction under the real
        # apiserver)
        self._history: collections.deque = collections.deque()
        # undo log: (rv, kind, key, prev_bytes|None) — each write's state
        # BEFORE the event, same window as the watch cache. Lets a
        # paginated LIST serve continuation pages from a consistent
        # snapshot at the continue token's revision (what the real
        # apiserver reads from etcd MVCC) by rolling the live view back —
        # and, since the store sharded, lets EVERY list/snapshot roll its
        # sequential per-shard walk back to one consistent revision.
        self._undo: collections.deque = collections.deque()
        self._compacted_rv = 0
        # the serialize-once broadcast ring: each watch event is encoded
        # exactly once into _ring; watchers hold cursors (absolute
        # sequences; base = _ring_next - len(_ring)). Trimmed to the
        # slowest live cursor, bounded by watch_backlog — a watcher whose
        # cursor lag exceeds the cap is closed reason="slow".
        self._ring: collections.deque = collections.deque()
        self._ring_next = 0
        self._ring_min = 0  # lazily-recomputed min live cursor estimate
        #: kwok_watch_encode_total: ring appends — exactly one encode per
        #: event, the serialize-once proof the parity twin reads
        self.encode_total = 0
        # per-kind object counts (kept under the ring lock so limit=1
        # population polls read a count consistent with the list revision)
        self._counts: dict[str, int] = {k: 0 for k in KINDS}
        # observability for tests
        self.patch_count = 0
        self.delete_count = 0
        # ring-cursor lag cap (PR 8's bounded-backlog semantics folded
        # into the ring); instance attr so tests/parity twins can tighten
        # it per store
        self.watch_backlog = WATCH_BACKLOG
        # kwok_watch_terminations_total{reason=}: ints bumped under the
        # ring lock (a registry child lock here would nest two leaves);
        # /metrics renders them via telemetry.apiserver_metrics
        self.watch_terminations = {"slow": 0, "deadline": 0}
        # kwok_watch_cursor_lag_events: final ring-cursor lag per watch
        # close (ISSUE 16's census surface); same ring-lock discipline
        self.lag_hist = LagHist()
        # phase timing + flight recorder (ISSUE 11); clock stamps gated
        # by KWOK_TPU_APISERVER_TIMING, counters (fanout pushes, lag
        # peak) always on — plain ints under the GIL like the rest
        self.timing = ApiserverTiming()
        # coordination.k8s.io/v1 leases (ISSUE 12): the leadership plane's
        # minimal dialect. Keyed (ns, name); each record keeps the wall
        # epochs the expiry arithmetic uses alongside the rendered RFC3339
        # stamps, so expiry never re-parses a timestamp. Leases live
        # OUTSIDE the watch/snapshot machinery by design (no events, no
        # dump entry): leadership is polled, not watched, and a restored
        # store must not resurrect an old holder. _lease_lock is held
        # ACROSS a fenced write's commit (86 → 87 → 88) so a takeover
        # PATCH can never interleave between fence check and commit.
        self._lease_lock = threading.RLock()
        self._leases: dict[tuple[str, str], dict] = {}

    # -- helpers ------------------------------------------------------------

    def _key(self, namespace, name):
        return (namespace or "", name)

    def _shard(self, kind: str, namespace, create: bool = True):
        ns = namespace or ""
        shards = self._shards  # local ref: restore swaps the registry
        sh = shards[kind].get(ns)
        if sh is None and create:
            with self._shard_idx_lock:
                sh = shards[kind].setdefault(ns, _Shard())
        return sh

    def _kind_shards(self, kind: str):
        """(ns, shard) pairs in namespace order — concatenating their
        sorted names yields the kind's global (ns, name) key order."""
        with self._shard_idx_lock:
            return sorted(self._shards[kind].items())

    def _shard_bytes_locked(self, sh: _Shard, name: str) -> bytes | None:
        """Serialized form of a stored object (caller holds the shard
        lock)."""
        b = sh.json.get(name)
        if b is None:
            obj = sh.objs.get(name)
            if obj is None:
                return None
            b = json.dumps(obj, separators=(",", ":")).encode()
            sh.json[name] = b
        return b

    def _commit_locked(
        self, sh: "_Shard | None", kind: str, key, obj: dict, type_: str,
        prev: "bytes | None", *, stamp_uid: bool = False,
    ) -> bytes:
        """The global event-order critical section (caller holds the
        SHARD's lock, so same-key writes are totally ordered): allocate
        the revision, serialize ONCE, record watch cache + undo, append
        the broadcast ring, wake watchers. Returns the new bytes."""
        timing = self.timing
        with self._ring_lock:
            self._rv += 1
            meta = obj.setdefault("metadata", {})
            if stamp_uid:
                meta.setdefault("uid", f"uid-{self._rv}")
            meta["resourceVersion"] = str(self._rv)
            data = json.dumps(obj, separators=(",", ":")).encode()
            if sh is not None and self._shards[kind].get(key[0]) is not sh:
                # a restore swapped the registry while this write held
                # its (now orphaned) shard: the registry swap happens
                # under THIS lock, so the check is race-free. The client
                # sees the same outcome the old atomic store gave —
                # committed, then wiped by the restore — so answer with
                # the serialized object but record NOTHING: no counts
                # (the restore reset them), no watch-cache/undo entry
                # (compacted), no ring event (watchers were closed) — a
                # ghost event here would be exactly the silent
                # divergence the drift auditor hunts.
                return data
            if sh is not None and type_ != DELETED:
                sh.json[key[1]] = data
            if RV_WINDOW > 0:
                self._history.append((self._rv, kind, type_, data))
                while len(self._history) > RV_WINDOW:
                    self._compacted_rv = max(
                        self._compacted_rv, self._history.popleft()[0]
                    )
                self._undo.append((self._rv, kind, key, prev))
                while self._undo and self._undo[0][0] <= self._compacted_rv:
                    self._undo.popleft()
            if type_ == ADDED:
                self._counts[kind] += 1
            elif type_ == DELETED:
                self._counts[kind] -= 1
            # fanout (ISSUE 13): ONE encode + ring append per event no
            # matter how many watchers consume it; the per-watcher
            # filter/write cost moved to the watcher threads. The push
            # counter counts deliveries-to-be (events x live watchers of
            # the kind) so fanout_sum / fanout_total is the AMORTIZED
            # per-watcher-push cost; both always on, clocks gated.
            nw = self._kind_watchers.get(kind, 0)
            if nw > 0:
                t0 = time.perf_counter() if timing.enabled else None
                self._ring.append(_RingEv(kind, type_, _event_line(type_, data)))
                self._ring_next += 1
                self.encode_total += 1
                timing.fanout_pushes += nw
                self._ring_trim_locked()
                self._ring_cond.notify_all()
                if t0 is not None:
                    timing.note_fanout(time.perf_counter() - t0)
            return data

    def _ring_trim_locked(self) -> None:
        """Trim consumed ring entries and enforce the lag cap (caller
        holds the ring lock): entries every live watcher consumed are
        dropped; once the ring outgrows ``watch_backlog`` the lagging
        watchers (cursor more than the cap behind) are slow-closed and
        their backlog reclaimed — PR 8's bounded-buffer drop/close
        semantics as ring-cursor lag. The peak watermark records the
        deepest retained lag, clamped to the cap on a termination, so
        fleet-check's gate (peak <= cap) keeps its meaning."""
        bl = self.watch_backlog
        while self._ring:
            base = self._ring_next - len(self._ring)
            if self._ring_min <= base:
                self._watches = [w for w in self._watches if not w.stopped]
                self._ring_min = min(
                    (w.cursor for w in self._watches),
                    default=self._ring_next,
                )
            if self._ring_min > base:
                self._ring.popleft()
                continue
            if bl > 0 and len(self._ring) > bl:
                lagged = False
                for w in self._watches:
                    if not w.stopped and self._ring_next - w.cursor > bl:
                        self._close_watch_locked(w, terminated="slow")
                        lagged = True
                self._ring_min = 0
                if bl > self.timing.backlog_peak:
                    self.timing.backlog_peak = bl
                if not lagged:
                    break  # safety: nobody to blame, stop trimming
                continue
            if len(self._ring) > self.timing.backlog_peak:
                self.timing.backlog_peak = len(self._ring)
            break

    def _close_watch_locked(self, w: _Watch, terminated=None) -> None:
        """Caller holds the ring lock. A slow termination DROPS the
        backlog (cursor jumps to head — 410-class recovery); a graceful
        stop still delivers events queued before the stop point — they
        are moved into the watch's PRIVATE replay now, because the ring
        trim stops retaining for stopped watches the moment this
        returns (shared refs, bounded by the live ring size)."""
        if w.stopped:
            return
        w.stopped = True
        # census: the stream's FINAL lag, observed before any cursor jump
        # (a slow close records the overflow that killed it, a graceful
        # close the tail it still had to drain)
        self.lag_hist.observe(max(0, self._ring_next - w.cursor))
        if terminated:
            w.terminated = terminated
            w.cursor = self._ring_next
            w.stop_seq = w.cursor
            self.watch_terminations[terminated] = (
                self.watch_terminations.get(terminated, 0) + 1
            )
        else:
            base = self._ring_next - len(self._ring)
            for seq in range(max(w.cursor, base), self._ring_next):
                ev = self._ring[seq - base]
                if w._takes(ev):
                    w.replay.append(ev)
            w.cursor = self._ring_next
            w.stop_seq = w.cursor
        self._kind_watchers[w.kind] = self._kind_watchers.get(w.kind, 1) - 1

    def count_termination(self, reason: str) -> None:
        """Record a server-side watch close (slow-consumer overflow or
        timeoutSeconds expiry) for /metrics."""
        with self._ring_lock:
            self.watch_terminations[reason] = (
                self.watch_terminations.get(reason, 0) + 1
            )

    def watch_backlogs(self) -> list:
        """Live per-watcher ring-cursor lags (resume replay stays
        cap-exempt and uncounted); thin view over ring_stats()."""
        return self.ring_stats()[0]

    def ring_stats(self) -> tuple:
        """(lags, lag_peak, encode_total) for /metrics — one consistent
        ring-lock read."""
        with self._ring_lock:
            lags = [
                self._ring_next - w.cursor
                for w in self._watches if not w.stopped
            ]
            return lags, self.timing.backlog_peak, self.encode_total

    def watchers_doc(self, server: str = "mock") -> dict:
        """The ``GET /debug/watchers`` census (ISSUE 16): one consistent
        ring-lock read of every live watch — ring-cursor lag, private
        replay backlog, age, band, and the deterministic termination-risk
        class (none / lagging / at_risk against the backlog cap). Schema
        parity-pinned against apiserver.cc via
        kwok_tpu.telemetry.timeline.check_watchers."""
        now = time.time()
        cap = self.watch_backlog
        with self._ring_lock:
            watchers = []
            parked = 0
            for w in self._watches:
                if w.stopped:
                    continue
                lag = max(0, self._ring_next - w.cursor)
                replay = len(w.replay)
                if lag == 0 and replay == 0:
                    # fully drained: its delivery thread is parked in the
                    # ring condition wait — the per-watcher thread cost
                    # the C10k reactor rewrite exists to erase
                    parked += 1
                risk = (
                    "none" if lag == 0
                    else ("lagging" if lag <= cap // 2 else "at_risk")
                )
                watchers.append({
                    "kind": w.kind,
                    "lag_events": lag,
                    "replay_pending": replay,
                    "age_s": round(max(0.0, now - w.created_unix), 3),
                    "band": "none",  # watches are max-inflight exempt
                    "risk": risk,
                })
        return {
            "server": server,
            "backlog_cap": cap,
            "thread_per_watcher": True,
            "count": len(watchers),
            "parked_threads": parked,
            "watchers": watchers,
        }

    def compact(self) -> int:
        """Force watch-cache compaction NOW: any watch resuming from a
        revision BELOW the current one gets 410 Gone (resuming at exactly
        the compacted revision is still gap-free, matching etcd, whose
        compaction at X drops revisions below X), and continue tokens
        below it expire. Live watchers' undelivered ring events are NOT
        dropped — compaction expires resumes, not broadcasts. Returns the
        compacted revision. (Ops/test hook; the real apiserver compacts
        every 5 minutes.)"""
        with self._ring_lock:
            self._history.clear()
            self._undo.clear()
            self._compacted_rv = self._rv
            return self._compacted_rv

    def emit_bookmarks(self) -> int:
        """Append one BOOKMARK ring event (current store revision) per
        kind with opted-in live watches — the watch cache's periodic
        rv-advance for quiet watchers, encoded once per kind no matter the
        cohort size. The bookmark object carries ONLY kind/apiVersion/
        metadata.resourceVersion, like the real apiserver's. Called by the
        HTTP servers' interval timer (BOOKMARK_INTERVAL) and by tests
        directly. Returns how many watches were bookmarked."""
        sent = 0
        with self._ring_lock:
            rv = str(self._rv)
            kinds: dict[str, int] = {}
            for w in self._watches:
                if w.stopped or not w.bookmarks:
                    continue
                kinds[w.kind] = kinds.get(w.kind, 0) + 1
                sent += 1
            for kind in kinds:
                api = (
                    "rbac.authorization.k8s.io/v1"
                    if kind in (
                        "roles", "rolebindings",
                        "clusterroles", "clusterrolebindings",
                    )
                    else "v1"
                )
                data = json.dumps({
                    "kind": KIND_SINGULAR.get(kind, "Object"),
                    "apiVersion": api,
                    "metadata": {"resourceVersion": rv},
                }, separators=(",", ":")).encode()
                self._ring.append(
                    _RingEv(kind, BOOKMARK, _event_line(BOOKMARK, data),
                            bookmark=True)
                )
                self._ring_next += 1
                self.encode_total += 1
            if kinds:
                self._ring_trim_locked()
                self._ring_cond.notify_all()
        return sent

    # -- test-side API ------------------------------------------------------

    def _create_impl(self, kind: str, obj: dict) -> bytes:
        obj = copy.deepcopy(obj)
        meta = obj.setdefault("metadata", {})
        ns = meta.get("namespace")
        sh = self._shard(kind, ns)
        with sh._shard_lock:
            if "name" not in meta and meta.get("generateName"):
                # apiserver names.go semantics: generateName + 5-char
                # random suffix (kube-scheduler POSTs events this way).
                # The real apiserver 409s on a suffix collision and the
                # client retries; retrying server-side is equivalent and
                # can't silently overwrite an existing object. Resolved
                # under the shard lock, so the name stays unique through
                # the insert.
                import secrets

                while True:
                    name = meta["generateName"] + secrets.token_hex(3)[:5]
                    if name not in sh.objs:
                        break
                meta["name"] = name
            name = meta["name"]
            meta.setdefault("creationTimestamp", now_rfc3339())
            if name in sh.objs:
                # the real apiserver never overwrites on create (HTTP 409)
                raise AlreadyExists(f'{kind} "{name}" already exists')
            sh.objs[name] = obj
            data = self._commit_locked(
                sh, kind, self._key(ns, name), obj, ADDED, None,
                stamp_uid=True,
            )
        if kind == "events":
            self._evict_events_overflow()
        return data

    def _evict_events_overflow(self) -> None:
        """The real apiserver expires events on a ~1h etcd lease
        (re-leased on every write); the mock bounds the store by count —
        the least-recently-written event (smallest resourceVersion) is
        evicted after an insert pushes past the cap. Runs OUTSIDE the
        creating shard's critical section: the victim may live in another
        namespace shard, and shard locks never nest. cap <= 0 means
        unbounded. Mirrors apiserver.cc."""
        if EVENTS_CAP <= 0:
            return
        while True:
            with self._ring_lock:
                if self._counts["events"] <= EVENTS_CAP:
                    return
            victim = None  # (rv, ns, name, shard)
            for ns_, sh in self._kind_shards("events"):
                with sh._shard_lock:
                    for nm, o in sh.objs.items():
                        try:
                            r = int(
                                (o.get("metadata") or {})
                                .get("resourceVersion") or 0
                            )
                        except (TypeError, ValueError):
                            r = 0
                        if victim is None or r < victim[0]:
                            victim = (r, ns_, nm, sh)
            if victim is None:
                return
            _r, ns_, nm, sh = victim
            with sh._shard_lock:
                obj = sh.objs.pop(nm, None)
                if obj is None:
                    continue  # raced another eviction; re-check the cap
                prev = sh.json.pop(nm, None) or json.dumps(
                    obj, separators=(",", ":")
                ).encode()
                # deletion is a write: bump like the explicit DELETE
                # path, so the DELETED event gets its own revision
                # (rv-resuming watchers would otherwise never see it)
                self._commit_locked(
                    sh, "events", (ns_, nm), obj, DELETED, prev
                )

    def create(self, kind: str, obj: dict) -> dict:
        return json.loads(self._create_impl(kind, obj))

    def create_bytes(self, kind: str, obj: dict) -> bytes:
        """HTTP hot path: create + serialized response in one pass (no
        deepcopied return value)."""
        return self._create_impl(kind, obj)

    def bind(self, namespace, name, node: str) -> dict | None:
        """POST pods/NAME/binding — the real scheduler's bind call: sets
        spec.nodeName exactly once. Raises BindConflict when spec.nodeName
        is already set — even to the same node, matching the real
        apiserver's BindingREST (any retry after a bind conflicts)."""
        sh = self._shard("pods", namespace, create=False)
        if sh is None:
            return None
        with sh._shard_lock:
            obj = sh.objs.get(name)
            if obj is None:
                return None
            spec = obj.setdefault("spec", {})
            current = spec.get("nodeName")
            if current:
                raise BindConflict(
                    f'pod {name} is already assigned to node {current}'
                )
            prev = self._shard_bytes_locked(sh, name)
            spec["nodeName"] = node
            data = self._commit_locked(
                sh, "pods", self._key(namespace, name), obj, MODIFIED, prev
            )
            return json.loads(data)

    def update(self, kind: str, obj: dict) -> dict:
        obj = copy.deepcopy(obj)
        meta = obj.get("metadata") or {}
        ns, name = meta.get("namespace"), meta.get("name")
        sh = self._shard(kind, ns)
        with sh._shard_lock:
            if name not in sh.objs:
                raise KeyError(self._key(ns, name))
            prev = self._shard_bytes_locked(sh, name)
            sh.objs[name] = obj
            data = self._commit_locked(
                sh, kind, self._key(ns, name), obj, MODIFIED, prev
            )
            return json.loads(data)

    # -- KubeClient protocol ------------------------------------------------

    def list(self, kind, *, field_selector=None, label_selector=None):
        return json.loads(self.list_bytes(
            kind, field_selector=field_selector,
            label_selector=label_selector,
        ))["items"]

    def list_bytes(
        self,
        kind,
        *,
        field_selector=None,
        label_selector=None,
        limit: int = 0,
        continue_: str | None = None,
    ) -> bytes:
        """Serialized List response (HTTP hot path): joins per-object cached
        bytes — no deepcopies, no whole-list re-serialization per poll.

        Pagination follows the kube-apiserver chunking protocol
        (limit/continue, staging/src/k8s.io/apiserver pagination): objects
        are returned in stable key order and `metadata.continue` is an
        opaque token resuming strictly after the last returned key. The
        token carries the revision of the FIRST page; a compaction while
        paginating expires it (raises WatchExpired -> HTTP 410, the real
        apiserver's "continue token too old" contract).

        EVERY page — first or continuation — serves a CONSISTENT SNAPSHOT
        at one revision (what the real apiserver reads from etcd MVCC):
        the sharded store is walked shard by shard (shard locks never
        nest) and the per-shard snapshots are rolled back through the
        undo log to the list revision, so an object created mid-pagination
        (or mid-walk, by a concurrent writer on another shard) is excluded
        no matter where its key sorts, one deleted mid-walk still appears,
        and every page reports the first page's resourceVersion. With the
        watch cache disabled (RV_WINDOW <= 0) there is no undo log and the
        walk serves the live view."""
        sel = parse_selector(label_selector)
        last = None
        snap: dict = {}
        overlay: dict = {}
        for _attempt in range(4):
            with self._ring_lock:
                if continue_:
                    # opaque url-safe token (the real apiserver's continue
                    # is base64 too): rv \0 ns \0 name
                    try:
                        tok_rv, _, rest = (
                            base64.urlsafe_b64decode(continue_.encode())
                            .decode()
                            .partition("\x00")
                        )
                        rv_val = int(tok_rv)
                    except (ValueError, UnicodeDecodeError,
                            binascii.Error) as e:
                        raise MalformedContinue(str(e)) from e
                    if rv_val < 0:
                        raise MalformedContinue(f"negative revision {rv_val}")
                    ns, _, name = rest.partition("\x00")
                    if rv_val < self._compacted_rv:
                        raise WatchExpired(
                            f"continue token revision {tok_rv} has been "
                            f"compacted"
                        )
                    list_rv = rv_val  # consistency marker of page 1
                    last = (ns, name)
                else:
                    list_rv = self._rv
            # sequential per-shard snapshots: bytes are immutable, each
            # shard internally consistent; cross-shard skew is reconciled
            # by the rollback below. Selector matching happens HERE on
            # the live dicts (as the old single-lock walk did) so a
            # selector LIST never json.loads the whole kind — only
            # overlay-sourced entries are parsed, in the emit loop.
            need_obj = field_selector is not None or sel is not None
            snap.clear()
            for ns_, sh in self._kind_shards(kind):
                with sh._shard_lock:
                    for nm, obj in sh.objs.items():
                        if need_obj:
                            if not match_field_selector(
                                obj, field_selector
                            ):
                                continue
                            if sel is not None:
                                labels = (
                                    obj.get("metadata") or {}
                                ).get("labels") or {}
                                if not sel.matches(labels):
                                    continue
                        snap[(ns_, nm)] = self._shard_bytes_locked(sh, nm)
            with self._ring_lock:
                if RV_WINDOW > 0 and list_rv < self._compacted_rv:
                    if continue_:
                        raise WatchExpired(
                            f"continue token revision {list_rv} has been "
                            f"compacted"
                        )
                    if _attempt < 3:
                        snap.clear()
                        continue  # compaction raced the walk: retry fresh
                    # repeated compactions mid-walk (ops hammering
                    # /compact): serve the live walk rather than loop
                    overlay.clear()
                    break
                # roll the walk back to the list revision:
                # newest-to-oldest, so a key's final overlay value is the
                # prev of its EARLIEST post-revision event = its state at
                # the list revision (None = absent then)
                overlay: dict = {}
                for rv_u, k_u, key_u, prev in reversed(self._undo):
                    if rv_u <= list_rv:
                        break
                    if k_u == kind:
                        overlay[key_u] = prev
            break
        from_overlay: set = set()
        for k_, prev in overlay.items():
            if prev is None:
                snap.pop(k_, None)
                from_overlay.discard(k_)
            else:
                snap[k_] = prev
                from_overlay.add(k_)
        keys = sorted(snap)
        if last is not None:
            keys = [k_ for k_ in keys if k_ > last]

        chunks: list[bytes] = []
        token = ""
        remaining = 0
        # only the FIRST page scans past the cut (remainingItemCount
        # for limit=1 count pollers) — counting on every continuation
        # page would make a full paginated re-list quadratic
        count_rest = not continue_
        for pos, key in enumerate(keys):
            if limit and len(chunks) >= limit and not count_rest:
                break
            if need_obj and key in from_overlay:
                # rolled-back state replaced the (pre-matched) live one:
                # only these few entries ever pay a parse
                obj = json.loads(snap[key])
                if not match_field_selector(obj, field_selector):
                    continue
                if sel is not None:
                    labels = (obj.get("metadata") or {}).get("labels") or {}
                    if not sel.matches(labels):
                        continue
            if limit and len(chunks) >= limit:
                remaining += 1
                continue
            chunks.append(snap[key])
            if limit and len(chunks) >= limit and pos + 1 < len(keys):
                token = base64.urlsafe_b64encode(
                    f"{list_rv}\x00{key[0]}\x00{key[1]}".encode()
                ).decode()
        # every page of one paginated list reports page 1's revision
        # (the real apiserver's paged LIST contract)
        rv = str(list_rv)
        meta = f'{{"resourceVersion":"{rv}"'.encode()
        if token and (remaining if count_rest else True):
            meta += b',"continue":' + json.dumps(token).encode()
        if limit and count_rest and remaining:
            meta += b',"remainingItemCount":' + str(remaining).encode()
        meta += b"}"
        return (
            b'{"kind":"List","apiVersion":"v1","metadata":' + meta
            + b',"items":[' + b",".join(chunks) + b"]}"
        )

    def get_bytes(self, kind, namespace, name) -> bytes | None:
        sh = self._shard(kind, namespace, create=False)
        if sh is None:
            return None
        with sh._shard_lock:
            return self._shard_bytes_locked(sh, name)

    def watch(
        self,
        kind,
        *,
        field_selector=None,
        label_selector=None,
        resource_version=None,
        allow_bookmarks=False,
    ):
        """resource_version > 0 resumes strictly after that revision: the
        watch cache replays the gap, then the watch goes live. A revision
        below the compaction floor raises WatchExpired — the client must
        re-list (410 Gone semantics). A revision AHEAD of the store raises
        TooLargeResourceVersion (HTTP 504 "Too large resource version",
        retry semantics — the real apiserver's watch cache blocks up to
        ~3s waiting to catch up first; the mock answers immediately, a
        documented timing divergence). A non-numeric revision raises
        ValueError (the HTTP facade answers 400, like the real
        apiserver)."""
        w = _Watch(self, kind, field_selector, label_selector)
        w.bookmarks = bool(allow_bookmarks)
        rv = int(resource_version or 0)
        if rv < 0:
            # the real apiserver rejects negative revisions as invalid
            # (400), it does not claim they expired; the C++ mirror's
            # digit check does the same
            raise ValueError(f"invalid resourceVersion: {rv}")
        with self._ring_lock:
            if rv:
                if rv > self._rv:
                    raise TooLargeResourceVersion(rv, self._rv)
                if rv < self._compacted_rv or RV_WINDOW <= 0:
                    raise WatchExpired(f"too old resource version: {rv}")
                for hrv, hkind, htype, hdata in self._history:
                    if hrv <= rv or hkind != kind:
                        continue
                    hobj = json.loads(hdata)  # fresh dict: no copy needed
                    if w._matches(hobj):
                        # cap-exempt resume replay (bounded by RV_WINDOW)
                        w.replay.append(
                            _RingEv(kind, htype, _event_line(htype, hdata))
                        )
            # cursor starts at the ring head, atomically with the replay
            # collection: nothing between the cache gap and going live
            w.cursor = self._ring_next
            self._watches.append(w)
            self._kind_watchers[kind] = self._kind_watchers.get(kind, 0) + 1
        return w

    def get(self, kind, namespace, name):
        b = self.get_bytes(kind, namespace, name)
        return json.loads(b) if b is not None else None

    def patch_status(self, kind, namespace, name, patch):
        # explicit class call: subclasses (the rig's OplogStore) override
        # BOTH verbs to note their oplog — virtual dispatch here would
        # note one client patch twice
        b = FakeKube.patch_status_bytes(self, kind, namespace, name, patch)
        return json.loads(b) if b is not None else None

    def patch_status_bytes(self, kind, namespace, name, patch) -> bytes | None:
        """HTTP hot path: patch + serialized response in one shard-lock
        hold."""
        if isinstance(patch, (bytes, bytearray, memoryview)):
            patch = json.loads(bytes(patch))
        sh = self._shard(kind, namespace, create=False)
        if sh is None:
            return None
        with sh._shard_lock:
            obj = sh.objs.get(name)
            if obj is None:
                return None
            prev = self._shard_bytes_locked(sh, name)
            status = obj.get("status") or {}
            obj["status"] = strategic_merge(status, patch.get("status", patch))
            self.patch_count += 1
            return self._commit_locked(
                sh, kind, self._key(namespace, name), obj, MODIFIED, prev
            )

    def patch_meta(self, kind, namespace, name, patch):
        """Merge-patch metadata (and spec — covers the scheduler's pod
        binding, which the soak rig's binder issues as a spec.nodeName
        patch; real schedulers use POST .../binding to the same effect)."""
        b = self.patch_meta_bytes(kind, namespace, name, patch)
        return json.loads(b) if b is not None else None

    def patch_meta_bytes(self, kind, namespace, name, patch) -> bytes | None:
        """HTTP hot path: patch + serialized response in one shard-lock
        hold, so the response is exactly the object this patch produced."""
        sh = self._shard(kind, namespace, create=False)
        if sh is None:
            return None
        with sh._shard_lock:
            obj = sh.objs.get(name)
            if obj is None:
                return None
            prev = self._shard_bytes_locked(sh, name)
            for section in ("metadata", "spec"):
                sec_patch = (patch or {}).get(section)
                if not sec_patch:
                    continue
                sec = obj.setdefault(section, {})
                for k, v in sec_patch.items():
                    if v is None:
                        sec.pop(k, None)
                    else:
                        sec[k] = copy.deepcopy(v)
            return self._commit_locked(
                sh, kind, self._key(namespace, name), obj, MODIFIED, prev
            )

    def dump(self) -> dict:
        """Serializable snapshot of the whole store — the mock's 'etcd
        snapshot' (cluster state IS store state, SURVEY.md section 3.5).
        Sharded-store walk, rolled back through the undo log to ONE
        revision across every kind; objects are ordered by (namespace,
        name), matching the C++ twin's sorted maps (parity-pinned by the
        snapshot-ordering twin)."""
        for _attempt in range(4):
            with self._ring_lock:
                rv_start = self._rv
            per_kind: dict[str, dict] = {}
            for kind in KINDS:
                snap: dict = {}
                for ns_, sh in self._kind_shards(kind):
                    with sh._shard_lock:
                        for nm in sh.objs:
                            snap[(ns_, nm)] = self._shard_bytes_locked(
                                sh, nm
                            )
                per_kind[kind] = snap
            with self._ring_lock:
                if RV_WINDOW > 0 and rv_start < self._compacted_rv \
                        and _attempt < 3:
                    continue  # compaction raced the walk: retry
                for rv_u, k_u, key_u, prev in reversed(self._undo):
                    if rv_u <= rv_start:
                        break
                    if prev is None:
                        per_kind[k_u].pop(key_u, None)
                    else:
                        per_kind[k_u][key_u] = prev
            break
        return {
            "resourceVersion": rv_start,
            "objects": {
                kind: [json.loads(snap[k_]) for k_ in sorted(snap)]
                for kind, snap in per_kind.items()
            },
        }

    def load(self, data: dict) -> None:
        """Replace the store from a dump(). The fresh shard registry is
        built OFF-lock and swapped in atomically (readers holding an old
        shard see the pre-restore world, never a torn one); all open
        watches are closed so clients re-list, like watchers reconnecting
        after an etcd restore."""
        new_shards: dict[str, dict[str, _Shard]] = {k: {} for k in KINDS}
        counts = {k: 0 for k in KINDS}
        for kind, objs in (data.get("objects") or {}).items():
            if kind not in new_shards:
                continue
            for obj in objs:
                meta = obj.get("metadata") or {}
                ns = meta.get("namespace") or ""
                sh = new_shards[kind].setdefault(ns, _Shard())
                sh.objs[meta.get("name")] = copy.deepcopy(obj)
                counts[kind] += 1
        with self._ring_lock:
            self._shards = new_shards
            self._counts = counts
            self._rv = max(self._rv, int(data.get("resourceVersion") or 0)) + 1
            # history predates the restore: compact so resumed watches and
            # continue tokens from the old world get 410 and re-list
            self._history.clear()
            self._undo.clear()
            self._compacted_rv = self._rv
            for w in self._watches:
                self._close_watch_locked(w)
            self._watches = []
            self._ring.clear()
            self._ring_min = self._ring_next
            self._ring_cond.notify_all()

    def stop_watches(self) -> None:
        """Close every open watch stream (apiserver shutdown semantics):
        closed under the ring lock (pure flag flips — no I/O), so a
        concurrently-registering watch either lands before the sweep (and
        is stopped) or after (and belongs to whatever serves the store
        next)."""
        with self._ring_lock:
            for w in self._watches:
                try:
                    self._close_watch_locked(w)
                except Exception:
                    # shutdown race with a client tearing the stream down
                    swallowed("mockserver.watch_stop")
            self._watches = []
            self._ring_cond.notify_all()

    def delete(self, kind, namespace, name, grace_seconds: int | None = 0):
        """grace_seconds=None applies the server default: for pods,
        spec.terminationGracePeriodSeconds or 30 (real apiserver
        DeleteOptions semantics); other kinds delete immediately."""
        sh = self._shard(kind, namespace, create=False)
        if sh is None:
            return
        with sh._shard_lock:
            obj = sh.objs.get(name)
            if obj is None:
                return
            if grace_seconds is None:
                grace_seconds = 0
                if kind == "pods":
                    tgps = (obj.get("spec") or {}).get(
                        "terminationGracePeriodSeconds"
                    )
                    grace_seconds = int(tgps) if tgps is not None else 30
            prev = self._shard_bytes_locked(sh, name)
            meta = obj.setdefault("metadata", {})
            finalizers = meta.get("finalizers") or []
            if kind == "pods" and (grace_seconds > 0 or finalizers):
                # graceful: mark for deletion, wait for the kubelet (the
                # engine) to force-delete / strip finalizers
                if "deletionTimestamp" not in meta:
                    meta["deletionTimestamp"] = now_rfc3339()
                meta["deletionGracePeriodSeconds"] = grace_seconds
                self._commit_locked(
                    sh, kind, self._key(namespace, name), obj, MODIFIED,
                    prev,
                )
                return
            del sh.objs[name]
            sh.json.pop(name, None)
            self.delete_count += 1
            self._commit_locked(
                sh, kind, self._key(namespace, name), obj, DELETED, prev
            )

    # -- coordination.k8s.io/v1 leases (ISSUE 12) ---------------------------
    #
    # The minimal Lease dialect both mock apiservers speak byte-for-byte
    # (parity twins in tests/test_native_apiserver.py): create / GET /
    # PATCH-renew with holderIdentity + leaseDurationSeconds +
    # leaseTransitions. The SERVER is the one clock authority: it stamps
    # acquireTime/renewTime when it processes the write and judges expiry
    # against its own wall clock, so a standby never has to trust a dead
    # primary's clock — it simply keeps PATCHing with its own identity and
    # is answered 409 Conflict until the lease genuinely expired
    # (client-go leader-election shape over the Lease object, with the
    # optimistic-concurrency Update replaced by server-arbitrated PATCH).

    def _lease_render(self, ns: str, name: str, lease: dict) -> bytes:
        return json.dumps({
            "kind": "Lease",
            "apiVersion": "coordination.k8s.io/v1",
            "metadata": {
                "name": name,
                "namespace": ns,
                "creationTimestamp": lease["created"],
                "uid": lease["uid"],
                "resourceVersion": str(lease["rv"]),
            },
            "spec": {
                "holderIdentity": lease["holder"],
                "leaseDurationSeconds": lease["duration"],
                "acquireTime": lease["acquire_str"],
                "renewTime": lease["renew_str"],
                "leaseTransitions": lease["transitions"],
            },
        }, separators=(",", ":")).encode()

    @staticmethod
    def _lease_spec(spec) -> tuple[str, int]:
        """(holderIdentity, leaseDurationSeconds) from a request spec,
        tolerantly: hostile bodies must never crash the handler. Parity
        with the C++ twin on every shape our clients and the twins pin:
        non-object specs read empty, integers and plain finite floats
        truncate, leading-integer strings parse like atol ("2.5" -> 2),
        booleans and infinities read 0. Exponent-form NUMBER tokens
        (1e3) are a documented tolerance: C++ atol sees the raw token's
        leading digits where Python sees the parsed value — both
        bounded, neither crashing."""
        if not isinstance(spec, dict):
            return "", 0
        holder = spec.get("holderIdentity")
        holder = holder if isinstance(holder, str) else ""
        raw = spec.get("leaseDurationSeconds")
        duration = 0
        if isinstance(raw, bool):
            duration = 0  # C++ BOOL is neither NUM nor STR
        elif isinstance(raw, (int, float)):
            try:
                duration = int(raw)
            except (OverflowError, ValueError):  # inf / nan
                duration = 0
        elif isinstance(raw, str):
            m = re.match(r"\s*[-+]?\d+", raw)
            duration = int(m.group()) if m else 0
        return holder, duration

    @staticmethod
    def _lease_expired(lease: dict, now: float) -> bool:
        """Server-clock expiry: a lease with no holder is vacant (same as
        expired); otherwise it expires once renewTime + duration has
        passed. duration <= 0 means instantly reacquirable."""
        if not lease["holder"]:
            return True
        return now >= lease["renew"] + max(0, lease["duration"])

    def lease_create(self, ns: str, name: str, spec: dict) -> tuple[int, bytes]:
        """POST .../leases — acquire by creation (leaseTransitions starts
        at 0, like the real object on first acquisition). An existing
        lease answers 409 AlreadyExists exactly like any other create."""
        holder, duration = self._lease_spec(spec or {})
        with self._lease_lock:
            key = (ns or "", name)
            if key in self._leases:
                return 409, json.dumps({
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Failure",
                    "message": f'leases "{name}" already exists',
                    "reason": "AlreadyExists", "code": 409,
                }, separators=(",", ":")).encode()
            now = time.time()
            stamp = now_rfc3339()
            with self._ring_lock:  # lease writes share the store clock
                self._rv += 1
                rv = self._rv
            lease = {
                "holder": holder,
                "duration": duration,
                "acquire": now,
                "renew": now,
                "transitions": 0,
                "created": stamp,
                "uid": f"uid-{rv}",
                "rv": rv,
                "acquire_str": stamp,
                "renew_str": stamp,
            }
            self._leases[key] = lease
            return 201, self._lease_render(ns, name, lease)

    def lease_get(self, ns: str, name: str) -> tuple[int, bytes]:
        with self._lease_lock:
            lease = self._leases.get((ns or "", name))
            if lease is None:
                return 404, b'{"kind":"Status","code":404}'
            return 200, self._lease_render(ns, name, lease)

    def lease_renew(self, ns: str, name: str, spec: dict) -> tuple[int, bytes]:
        """PATCH .../leases/NAME — renew-or-acquire, arbitrated under the
        store lock by the server's own clock:

        - same holder: renewTime advances (a renew);
        - different holder, lease NOT expired: 409 Conflict — both the
          standby's premature grab and the revived zombie's stale renew
          land here (conflict-on-stolen-holder);
        - different holder, lease expired: acquisition — holderIdentity
          flips, acquireTime/renewTime restamp, leaseTransitions += 1.
        """
        holder, duration = self._lease_spec(spec or {})
        with self._lease_lock:
            key = (ns or "", name)
            lease = self._leases.get(key)
            if lease is None:
                return 404, b'{"kind":"Status","code":404}'
            now = time.time()
            if holder != lease["holder"] and not self._lease_expired(
                lease, now
            ):
                return 409, json.dumps({
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Failure",
                    "message": (
                        f'lease "{ns}/{name}" is held by '
                        f'"{lease["holder"]}" and has not expired'
                    ),
                    "reason": "Conflict", "code": 409,
                }, separators=(",", ":")).encode()
            stamp = now_rfc3339()
            if holder != lease["holder"]:
                lease["holder"] = holder
                lease["acquire"] = now
                lease["acquire_str"] = stamp
                lease["transitions"] += 1
            lease["renew"] = now
            lease["renew_str"] = stamp
            if duration > 0:
                lease["duration"] = duration
            with self._ring_lock:  # lease writes share the store clock
                self._rv += 1
                lease["rv"] = self._rv
            return 200, self._lease_render(ns, name, lease)

    def lease_held(self, ns: str, name: str, holder: str) -> bool:
        """The fencing check (FENCING_HEADER): is this lease currently
        held by this identity and unexpired, on the server's clock? One
        dict lookup under the lease lock — only writes that CARRY the
        header ever pay it. The HTTP facade holds _lease_lock ACROSS the
        fenced commit (re-entrant here), so a takeover PATCH serializes
        against the whole check+commit, not just this lookup."""
        with self._lease_lock:
            lease = self._leases.get((ns or "", name))
            if lease is None or lease["holder"] != holder:
                return False
            return not self._lease_expired(lease, time.time())




_PATHS = re.compile(
    r"^/api/v1(?:/namespaces/(?P<ns>[^/]+))?/(?P<kind>nodes|pods|events)"
    r"(?:/(?P<name>[^/]+))?(?:/(?P<sub>status|binding|log))?$"
)
_RBAC_PATHS = re.compile(
    r"^/apis/rbac\.authorization\.k8s\.io/v1"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<kind>roles|rolebindings|clusterroles|clusterrolebindings)"
    r"(?:/(?P<name>[^/]+))?(?P<sub>)?$"
)
# a real v1.19+ kube-scheduler records events via events.k8s.io/v1, not
# core v1; both groups route to the one events store (the real apiserver
# mirrors them)
_EVENTS_PATHS = re.compile(
    r"^/apis/events\.k8s\.io/v1"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<kind>events)(?:/(?P<name>[^/]+))?(?P<sub>)?$"
)
# coordination.k8s.io/v1 Lease: the leadership plane's object (ISSUE 12).
# Deliberately OUTSIDE _match_path: leases are served by a dedicated
# minimal dialect (create / GET / PATCH-renew, no list/watch/delete), stay
# exempt from max-inflight admission and phase timing like every other
# non-resource path, and never enter snapshots — both servers agree.
_LEASE_PATHS = re.compile(
    r"^/apis/coordination\.k8s\.io/v1"
    r"/namespaces/(?P<ns>[^/]+)/leases(?:/(?P<name>[^/]+))?$"
)

#: mutating requests may carry this header naming the lease the writer
#: believes it holds, as ``<namespace>/<name>/<holderIdentity>``; the
#: server rejects the write 409 when that lease is NOT currently held by
#: that identity — server-side write fencing, the authoritative half of
#: the HA plane's zombie protection (a paused-and-revived old primary's
#: in-flight writes die HERE even when they slipped past the client-side
#: fence check before the pause). Absent header = zero cost, no check.
FENCING_HEADER = "X-Kwok-Lease-Holder"


def _match_path(path: str):
    m = (
        _PATHS.match(path)
        or _RBAC_PATHS.match(path)
        or _EVENTS_PATHS.match(path)
    )
    # subresources exist only where the real apiserver serves them:
    # binding under pods, status under nodes/pods (404 otherwise)
    if m and m.group("sub") == "binding" and m.group("kind") != "pods":
        return None
    if m and m.group("sub") == "status" and m.group("kind") not in ("nodes", "pods"):
        return None
    if m and m.group("sub") == "log" and m.group("kind") != "pods":
        return None
    return m


def pod_log_status(
    store, ns: str | None, name: str, container: str | None
) -> tuple[dict, int]:
    """The apiserver's answer to GET pods/NAME/log against a kwok cluster.

    Fake pods have no kubelet: the real apiserver proxies the request to
    the node's InternalIP:10250 and surfaces the dial failure as a 500
    Status — that exact dialect is what kubectl users see on upstream
    kwok, so both mock apiservers reproduce it (an unscheduled pod gets
    the 400 'not have a host assigned' answer instead)."""
    pod = store.get("pods", ns, name)
    if pod is None:
        return {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "message": f'pods "{name}" not found',
            "reason": "NotFound", "code": 404,
        }, 404
    node_name = (pod.get("spec") or {}).get("nodeName") or ""
    if not node_name:
        return {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "message": f"pod {name} does not have a host assigned",
            "reason": "BadRequest", "code": 400,
        }, 400
    if not container:
        containers = (pod.get("spec") or {}).get("containers") or []
        container = (containers[0].get("name") if containers else "") or ""
    node = store.get("nodes", None, node_name)
    ip = node_name
    for addr in ((node or {}).get("status") or {}).get("addresses") or []:
        if addr.get("type") == "InternalIP" and addr.get("address"):
            ip = addr["address"]
            break
    url = f"https://{ip}:10250/containerLogs/{ns or ''}/{name}/{container}"
    return {
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "message": (
            f'Get "{url}": dial tcp {ip}:10250: connect: connection refused'
        ),
        "code": 500,
    }, 500


def _api_resource(name: str, kind: str, namespaced: bool, subs=()):
    out = [{"name": name, "singularName": "", "namespaced": namespaced,
            "kind": kind, "verbs": ["create", "delete", "get", "list",
                                    "patch", "update", "watch"]}]
    for sub in subs:
        out.append({"name": f"{name}/{sub}", "singularName": "",
                    "namespaced": namespaced, "kind": kind,
                    "verbs": ["get", "patch", "update"]
                    if sub == "status" else ["create"]})
    return out


# Discovery documents: enough for real clients (kubectl, kube-scheduler's
# restmapper) to resolve the kinds this server stores. Served by both mock
# apiservers; parity-tested.
DISCOVERY: dict[str, dict] = {
    "/version": {
        "major": "1", "minor": "26", "gitVersion": "v1.26.0-kwok-tpu",
        "platform": "linux/amd64",
    },
    "/api": {"kind": "APIVersions", "versions": ["v1"]},
    "/apis": {
        "kind": "APIGroupList",
        "apiVersion": "v1",
        "groups": [
            {
                "name": "rbac.authorization.k8s.io",
                "versions": [
                    {"groupVersion": "rbac.authorization.k8s.io/v1",
                     "version": "v1"}
                ],
                "preferredVersion": {
                    "groupVersion": "rbac.authorization.k8s.io/v1",
                    "version": "v1",
                },
            },
            {
                "name": "events.k8s.io",
                "versions": [
                    {"groupVersion": "events.k8s.io/v1", "version": "v1"}
                ],
                "preferredVersion": {
                    "groupVersion": "events.k8s.io/v1", "version": "v1"
                },
            },
            {
                "name": "coordination.k8s.io",
                "versions": [
                    {"groupVersion": "coordination.k8s.io/v1",
                     "version": "v1"}
                ],
                "preferredVersion": {
                    "groupVersion": "coordination.k8s.io/v1",
                    "version": "v1",
                },
            },
        ],
    },
    "/api/v1": {
        "kind": "APIResourceList",
        "groupVersion": "v1",
        "resources": (
            _api_resource("nodes", "Node", False, subs=("status",))
            + _api_resource("pods", "Pod", True, subs=("status", "binding"))
            + _api_resource("events", "Event", True)
        ),
    },
    "/apis/rbac.authorization.k8s.io/v1": {
        "kind": "APIResourceList",
        "groupVersion": "rbac.authorization.k8s.io/v1",
        "resources": (
            _api_resource("roles", "Role", True)
            + _api_resource("rolebindings", "RoleBinding", True)
            + _api_resource("clusterroles", "ClusterRole", False)
            + _api_resource("clusterrolebindings", "ClusterRoleBinding", False)
        ),
    },
    "/apis/events.k8s.io/v1": {
        "kind": "APIResourceList",
        "groupVersion": "events.k8s.io/v1",
        "resources": _api_resource("events", "Event", True),
    },
    "/apis/coordination.k8s.io/v1": {
        "kind": "APIResourceList",
        "groupVersion": "coordination.k8s.io/v1",
        # the minimal Lease dialect: create / get / patch only (no
        # list/watch/delete — leadership is polled, never watched)
        "resources": [
            {"name": "leases", "singularName": "", "namespaced": True,
             "kind": "Lease", "verbs": ["create", "get", "patch"]}
        ],
    },
}


# Bootstrap RBAC policy seeded when the cluster runs with
# --kube-authorization: a representative subset of the objects the real
# apiserver's bootstrap controller creates (cluster-admin & friends), plus
# the engine's own role mirroring kustomize/kwok/kwok-clusterrole.yaml.
# The authorization e2e case asserts all four kinds list non-empty, as the
# reference's does (test/kwokctl/kwokctl_authorization_test.sh:73-82).
_BOOTSTRAP_LABELS = {"kubernetes.io/bootstrapping": "rbac-defaults"}
BOOTSTRAP_RBAC: dict[str, list[dict]] = {
    "clusterroles": [
        {
            "metadata": {"name": "cluster-admin", "labels": _BOOTSTRAP_LABELS},
            "rules": [
                {"apiGroups": ["*"], "resources": ["*"], "verbs": ["*"]},
                {"nonResourceURLs": ["*"], "verbs": ["*"]},
            ],
        },
        {
            "metadata": {"name": "system:discovery", "labels": _BOOTSTRAP_LABELS},
            "rules": [
                {
                    "nonResourceURLs": ["/api", "/api/*", "/apis", "/apis/*",
                                        "/healthz", "/version"],
                    "verbs": ["get"],
                }
            ],
        },
        {
            "metadata": {"name": "system:kwok-controller", "labels": _BOOTSTRAP_LABELS},
            "rules": [
                {
                    "apiGroups": [""],
                    "resources": ["nodes", "pods"],
                    "verbs": ["get", "watch", "list"],
                },
                {
                    "apiGroups": [""],
                    "resources": ["nodes/status", "pods/status"],
                    "verbs": ["update", "patch"],
                },
            ],
        },
    ],
    "clusterrolebindings": [
        {
            "metadata": {"name": "cluster-admin", "labels": _BOOTSTRAP_LABELS},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "cluster-admin",
            },
            "subjects": [
                {"apiGroup": "rbac.authorization.k8s.io", "kind": "Group",
                 "name": "system:masters"}
            ],
        },
        {
            "metadata": {"name": "system:kwok-controller", "labels": _BOOTSTRAP_LABELS},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "system:kwok-controller",
            },
            "subjects": [
                {"kind": "ServiceAccount", "name": "kwok-controller",
                 "namespace": "kube-system"}
            ],
        },
    ],
    "roles": [
        {
            "metadata": {
                "name": "extension-apiserver-authentication-reader",
                "namespace": "kube-system",
                "labels": _BOOTSTRAP_LABELS,
            },
            "rules": [
                {"apiGroups": [""], "resources": ["configmaps"],
                 "resourceNames": ["extension-apiserver-authentication"],
                 "verbs": ["get", "list", "watch"]}
            ],
        },
    ],
    "rolebindings": [
        {
            "metadata": {
                "name": "system::extension-apiserver-authentication-reader",
                "namespace": "kube-system",
                "labels": _BOOTSTRAP_LABELS,
            },
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "Role",
                "name": "extension-apiserver-authentication-reader",
            },
            "subjects": [
                {"apiGroup": "rbac.authorization.k8s.io", "kind": "User",
                 "name": "system:kube-controller-manager"}
            ],
        },
    ],
}


def seed_bootstrap_rbac(store: FakeKube) -> None:
    """Create the bootstrap policy objects if absent (idempotent across
    restarts with a persisted --data-file)."""
    kind_names = {
        "clusterroles": "ClusterRole",
        "clusterrolebindings": "ClusterRoleBinding",
        "roles": "Role",
        "rolebindings": "RoleBinding",
    }
    for kind, objs in BOOTSTRAP_RBAC.items():
        for obj in objs:
            meta = obj["metadata"]
            if store.get(kind, meta.get("namespace"), meta["name"]) is None:
                doc = {
                    "apiVersion": "rbac.authorization.k8s.io/v1",
                    "kind": kind_names[kind],
                    **copy.deepcopy(obj),
                }
                store.create(kind, doc)


def _expired_status(message: str) -> dict:
    """The kube-apiserver 410 Status body (reason Expired) shared by the
    expired-watch ERROR event and the expired-continue list response."""
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "message": message,
        "reason": "Expired",
        "code": 410,
    }


def _too_large_rv_status(e: TooLargeResourceVersion) -> dict:
    """The kube-apiserver's answer to a watch resume AHEAD of its store:
    504 reason Timeout with a ResourceVersionTooLarge cause and a
    retryAfterSeconds hint (storage.NewTooLargeResourceVersionError →
    apierrors.NewTimeoutError) — retry semantics, not Expired."""
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "message": str(e),
        "reason": "Timeout",
        "details": {
            "causes": [
                {
                    "reason": "ResourceVersionTooLarge",
                    "message": "Too large resource version",
                }
            ],
            "retryAfterSeconds": int(e.retry_after),
        },
        "code": 504,
    }


class _Admission:
    """Two-band max-inflight admission (kube-apiserver's
    --max-requests-inflight bands, KEP-1040's reject-don't-queue shape).

    A slot is held for the request's full lifetime — including reading
    its body and writing its response — so a band saturates exactly when
    that many requests are genuinely in flight. ``_adm_lock`` guards only
    the counters and nothing is ever acquired under it (kwoklint level
    84, documented in docs/static-analysis.md)."""

    def __init__(self, readonly_max: int, mutating_max: int) -> None:
        self.limits = {"readonly": readonly_max, "mutating": mutating_max}
        self.inflight = {"readonly": 0, "mutating": 0}
        self.rejected = {"readonly": 0, "mutating": 0}
        self._adm_lock = threading.Lock()

    def try_acquire(self, band: str) -> bool:
        with self._adm_lock:
            limit = self.limits[band]
            if limit > 0 and self.inflight[band] >= limit:
                self.rejected[band] += 1
                return False
            self.inflight[band] += 1
            return True

    def release(self, band: str) -> None:
        with self._adm_lock:
            self.inflight[band] -= 1


def _admission_band(method: str, path: str, query: str) -> "str | None":
    """The max-inflight band a request is admitted through, or None when
    exempt. Resource requests only (like the real apiserver: /healthz,
    /metrics, discovery and the snapshot/restore/compact ops hooks stay
    outside); watches are long-running and exempt
    (longRunningRequestCheck), bounded by the per-watcher send buffer
    instead."""
    if method == "GET":
        if not _match_path(path):
            return None
        q = urllib.parse.parse_qs(query)
        if (q.get("watch") or ["false"])[0] in ("true", "1"):
            return None
        return "readonly"
    if method in ("POST", "PATCH", "DELETE") and _match_path(path):
        return "mutating"
    return None


class _HandshakeFailed(Exception):
    """TLS handshake rejected/timed out — normal under mTLS (cert-less
    dials, mis-scheme probes); closed quietly, no traceback."""


class _Server(ThreadingHTTPServer):
    # the default backlog of 5 drops connections under bursty load
    # (benchmark cases open ~1k sockets while patch workers hold 16 more)
    request_queue_size = 256
    daemon_threads = True

    def handle_error(self, request, client_address):
        import sys

        if isinstance(sys.exc_info()[1], _HandshakeFailed):
            return
        super().handle_error(request, client_address)


class HttpFakeApiserver:
    def __init__(
        self,
        store: FakeKube | None = None,
        port: int = 0,
        address: str = "127.0.0.1",
        audit_log_path: str | None = None,
        token: str | None = None,
        tls_cert_file: str | None = None,
        tls_key_file: str | None = None,
        client_ca_file: str | None = None,
        max_inflight: int | None = None,
        max_mutating_inflight: int | None = None,
    ) -> None:
        self.store = store or FakeKube()
        # two-band overload admission; None falls back to the env knobs
        # (KWOK_TPU_MAX_INFLIGHT / KWOK_TPU_MAX_MUTATING_INFLIGHT). Both
        # bands off => no admission object, zero per-request cost.
        ro = MAX_INFLIGHT if max_inflight is None else int(max_inflight)
        mu = (
            MAX_MUTATING_INFLIGHT
            if max_mutating_inflight is None
            else int(max_mutating_inflight)
        )
        self._admission = _Admission(ro, mu) if (ro > 0 or mu > 0) else None
        # bearer-token authentication (kube-apiserver --token-auth-file):
        # when set, every request except /healthz must carry one of the
        # accepted tokens. The real apiserver accepts every row of the CSV,
        # so a str-or-iterable is normalized to a set here.
        self.tokens: frozenset[str] | None = (
            None if token is None
            else frozenset([token] if isinstance(token, str) else token)
        )
        self._audit_lock = threading.Lock()
        self._audit_file = None
        handler = self._make_handler()
        self.httpd = _Server((address, port), handler)  # bind before open:
        # a bind failure must not leak the audit file handle
        scheme = "http"
        if tls_cert_file or tls_key_file or client_ca_file:
            # the kube-apiserver secure port (--tls-cert-file /
            # --tls-private-key-file); --client-ca-file turns on mTLS, the
            # transport the binary runtime's secure mode uses. Half a TLS
            # config must fail hard, not silently serve plaintext on what
            # the operator believes is the secure port.
            import ssl

            if not (tls_cert_file and tls_key_file):
                self.httpd.server_close()
                raise ValueError(
                    "TLS needs both tls_cert_file and tls_key_file"
                )
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            try:
                ctx.load_cert_chain(tls_cert_file, tls_key_file)
                if client_ca_file:
                    ctx.load_verify_locations(client_ca_file)
                    ctx.verify_mode = ssl.CERT_REQUIRED
            except (OSError, ssl.SSLError):
                self.httpd.server_close()
                raise
            # handshake in the per-connection handler thread, NOT in the
            # accept loop — a client stalling mid-handshake must not block
            # every other accept (the engine's watch re-dials included)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
            scheme = "https"
        if audit_log_path:
            try:
                self._audit_file = open(audit_log_path, "a", encoding="utf-8")
            except OSError:
                self.httpd.server_close()
                raise
        self.port = self.httpd.server_address[1]
        host = "127.0.0.1" if address in ("", "0.0.0.0") else address
        self.url = f"{scheme}://{host}:{self.port}"
        self._thread: threading.Thread | None = None

    @staticmethod
    def _audit_verb(method: str, uri: str) -> str:
        """HTTP method + URI -> Kubernetes audit verb (get/list/watch/
        create/update/patch/delete), matching real apiserver audit Events."""
        method = method.upper()
        parsed = urllib.parse.urlparse(uri)
        if method == "GET":
            q = urllib.parse.parse_qs(parsed.query)
            if (q.get("watch") or ["false"])[0] in ("true", "1"):
                return "watch"
            m = _match_path(parsed.path)
            if m and not m.group("name"):
                return "list"
            return "get"
        return {
            "POST": "create",
            "PUT": "update",
            "PATCH": "patch",
            "DELETE": "delete",
        }.get(method, method.lower())

    def _audit(self, method: str, uri: str, code: int) -> None:
        """One audit.k8s.io/v1 Event line per request (the mock analogue of
        the apiserver's --audit-log-path; asserted by the audit e2e case)."""
        if self._audit_file is None:
            return
        line = json.dumps(
            {
                "kind": "Event",
                "apiVersion": "audit.k8s.io/v1",
                "level": "Metadata",
                "stage": "ResponseComplete",
                "verb": self._audit_verb(method, uri),
                "requestURI": uri,
                "responseStatus": {"code": code},
                "stageTimestamp": now_rfc3339(),
            }
        )
        with self._audit_lock:
            self._audit_file.write(line + "\n")
            self._audit_file.flush()

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="fake-apiserver"
        )
        self._thread.start()
        if BOOKMARK_INTERVAL > 0:
            # periodic rv-advance for quiet opted-in watches (the watch
            # cache's bookmark timer); Event-based so stop() is prompt
            self._bookmark_stop = threading.Event()

            def _bookmark_loop():
                while not self._bookmark_stop.wait(BOOKMARK_INTERVAL):
                    self.store.emit_bookmarks()

            self._bookmark_thread = threading.Thread(
                target=_bookmark_loop, daemon=True, name="bookmark-timer"
            )
            self._bookmark_thread.start()
        return self

    def stop(self):
        if getattr(self, "_bookmark_stop", None) is not None:
            self._bookmark_stop.set()
            self._bookmark_thread.join(timeout=5)
        self.httpd.shutdown()
        self.httpd.server_close()
        # a stopping apiserver terminates its watch streams; without this
        # the per-connection handler threads blocked on a quiet store
        # watch would keep their sockets open and clients would never see
        # the shutdown. (With a store shared across servers this closes
        # the other servers' streams too — their clients re-watch, the
        # same recovery as an apiserver restart.)
        self.store.stop_watches()
        if self._thread:
            self._thread.join(timeout=5)
        if self._audit_file is not None:
            self._audit_file.close()

    def _make_handler(self):
        store = self.store
        server_obj = self
        timing = store.timing

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # ---- phase timing (ISSUE 11): stamps mirror apiserver.cc.
            # parse_request runs after the request line was read, so the
            # read_headers phase excludes keep-alive idle time — exactly
            # like the C++ twin's first-bytes stamp.
            def parse_request(self):
                self._t_start = timing.begin_request()
                self._t_hdr = self._t_body = self._t_parse = None
                self._commit_s = 0.0
                self._parse_ran = False
                ok = super().parse_request()
                if ok and self._t_start is not None:
                    self._t_hdr = time.perf_counter()
                return ok

            def _commit(self, fn):
                """Run one store call, attributing its wall time to the
                commit phase (the under-the-lock work plus, via the tls
                accumulator, the fanout subset)."""
                if self._t_start is None:
                    return fn()
                t0 = time.perf_counter()
                try:
                    return fn()
                finally:
                    self._commit_s += time.perf_counter() - t0

            def _finish_timing(self, code: int, enc_s: float) -> None:
                t0 = getattr(self, "_t_start", None)
                if t0 is None:
                    return
                self._t_start = None  # one observation per request
                t_end = time.perf_counter()
                parsed = urllib.parse.urlparse(self.path)
                m = _match_path(parsed.path)
                if not m:
                    return  # ops/debug paths stay untimed (parity)
                t_hdr = self._t_hdr or t0
                t_body = self._t_body or t_hdr
                phases = {
                    "read_headers": t_hdr - t0,
                    "read_body": t_body - t_hdr,
                    "commit": self._commit_s,
                    "encode": enc_s,
                }
                if self._parse_ran:
                    phases["parse"] = self._t_parse - t_body
                fan = getattr(timing.tls, "fanout_s", 0.0) or 0.0
                if fan:
                    phases["fanout"] = fan
                total = t_end - t0
                # verb + band inline from the ONE parse/match above
                # (_audit_verb/_admission_band semantics for resource
                # paths, without re-parsing the URI per call)
                method = (self.command or "").upper()
                if method == "GET":
                    q = urllib.parse.parse_qs(parsed.query)
                    if (q.get("watch") or ["false"])[0] in ("true", "1"):
                        verb, band = "watch", "none"
                    else:
                        verb = "get" if m.group("name") else "list"
                        band = "readonly"
                else:
                    verb = {"POST": "create", "PUT": "update",
                            "PATCH": "patch", "DELETE": "delete"}.get(
                        method, method.lower()
                    )
                    band = (
                        "mutating"
                        if method in ("POST", "PATCH", "DELETE")
                        else "none"
                    )
                timing.observe_request(verb, total, phases)
                timing.record_flight(
                    self.command or "", self.path, code, band,
                    time.time() - total, total * 1e6,
                    {p: v * 1e6 for p, v in phases.items()},
                )

            def setup(self):  # noqa: D401
                # TLS handshake deferred out of the accept loop (see
                # __init__): complete it here, in this connection's thread.
                # Bounded, and rejections stay quiet — a silent or
                # cert-less client must neither pin this thread forever nor
                # spam the component log with tracebacks (ssl.SSLError and
                # socket.timeout are both OSError).
                if hasattr(self.request, "do_handshake"):
                    self.request.settimeout(10)
                    try:
                        self.request.do_handshake()
                    except OSError as e:
                        raise _HandshakeFailed() from e
                    self.request.settimeout(None)
                super().setup()
            # One TCP segment per response: Nagle on the server side holds
            # the body segment until the client ACKs the header segment, and
            # the client's delayed ACK turns every unary request into a
            # ~40ms stall (measured: 22 -> ~2900 req/s per connection).
            disable_nagle_algorithm = True
            wbufsize = -1  # fully buffer: headers+body leave in one write

            def log_message(self, *a):
                pass

            def log_request(self, code="-", size="-"):  # noqa: A002
                try:
                    server_obj._audit(self.command or "", self.path, int(code))
                except Exception:
                    # audit is best-effort; the request itself already
                    # succeeded/failed on its own terms
                    swallowed("mockserver.audit")

            def _send_json(self, obj, code=200):
                self._send_body(json.dumps(obj, separators=(",", ":")).encode(), code)

            def _send_body(self, body: bytes, code=200):
                t_enc = (
                    time.perf_counter()
                    if getattr(self, "_t_start", None) is not None
                    else None
                )
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                if t_enc is not None:
                    self._finish_timing(code, time.perf_counter() - t_enc)

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                timed = getattr(self, "_t_start", None) is not None
                if not n:
                    if timed:
                        self._t_body = time.perf_counter()
                    return None
                data = self.rfile.read(n)
                if timed:
                    self._t_body = time.perf_counter()
                try:
                    doc = json.loads(data or b"null")
                except ValueError as e:
                    # garbled or truncated (client died mid-body -> short
                    # read) request bytes: typed, answered 400 by the
                    # _admitted chokepoint — byte-identical to the C++
                    # mirror's JParser rejection, never a crash
                    raise _BadBody() from e
                if timed:
                    self._t_parse = time.perf_counter()
                    self._parse_ran = True
                return doc

            def _authorized(self) -> bool:
                """kube-apiserver token authn: /healthz stays anonymous (the
                components' --authorization-always-allow-paths contract);
                everything else 401s without the bearer token."""
                if server_obj.tokens is None:
                    return True
                got = self.headers.get("Authorization") or ""
                if got.startswith("Bearer ") and got[7:] in server_obj.tokens:
                    return True
                # drain the unread request body before responding, or the
                # next request on this keep-alive connection is parsed
                # starting at the leftover body bytes
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                self._send_json(
                    {
                        "kind": "Status",
                        "apiVersion": "v1",
                        "status": "Failure",
                        "reason": "Unauthorized",
                        "message": "Unauthorized",
                        "code": 401,
                    },
                    401,
                )
                return False

            def _reject_429(self):
                """Band saturated: 429 + Retry-After (never queue). The
                unread body is drained first so the next request on this
                keep-alive connection parses cleanly."""
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", RETRY_AFTER_SECONDS)
                self.send_header(
                    "Content-Length", str(len(TOO_MANY_REQUESTS_BODY))
                )
                self.end_headers()
                self.wfile.write(TOO_MANY_REQUESTS_BODY)
                self._finish_timing(429, 0.0)

            def _admitted(self, impl):
                """Run one request through max-inflight admission. The
                slot spans the request's whole lifetime (body read
                included — that is what makes saturation observable);
                exempt requests (watches, non-resource paths) and
                unconfigured servers skip straight through."""
                adm = server_obj._admission
                if adm is None:
                    return self._guarded(impl)
                parsed = urllib.parse.urlparse(self.path)
                band = _admission_band(
                    self.command or "", parsed.path, parsed.query
                )
                if band is None:
                    return self._guarded(impl)
                if not adm.try_acquire(band):
                    self._reject_429()
                    return
                try:
                    self._guarded(impl)
                finally:
                    adm.release(band)

            def _guarded(self, impl):
                """Hostile-byte backstop around one request handler: a
                garbled/truncated body answers the C++ mirror's exact 400
                Status (`{"kind":"Status","code":400}`); a connection
                that died before the answer could be written is closed
                quietly (the 400 had no reader) — either way the handler
                thread survives and the store lock was never entered
                (body parse precedes every store call)."""
                try:
                    impl()
                except _BadBody:
                    try:
                        self._send_json({"kind": "Status", "code": 400}, 400)
                    except OSError:
                        self.close_connection = True

            def _fenced_commit(self, fn):
                """Server-side write fencing (ISSUE 12): a mutating
                request carrying FENCING_HEADER names the lease its
                writer believes it holds as ``ns/name/holder``; when
                that lease is not currently held by that identity the
                write answers 409 Conflict instead of committing.
                The claim is evaluated and the commit performed under
                ONE store-lock hold (the RLock re-enters for the store
                call), so a takeover PATCH can never interleave between
                check and write — a revived zombie's in-flight bytes
                die here no matter when it was paused. Returns
                ``(fenced, result)``; the 409 is sent by the caller
                AFTER the lock drops (no socket I/O under the store
                lock). Requests without the header run ``fn()`` with
                one header lookup of overhead. Callers have already
                consumed the body (keep-alive stays parseable)."""
                hdr = self.headers.get(FENCING_HEADER)
                if not hdr:
                    return False, fn()
                # split exactly like the C++ twin's find-based parse so
                # malformed claims produce byte-identical 409 bodies:
                # no first slash -> all fields empty; no second slash ->
                # name/holder empty (ns keeps its prefix)
                ns, sep, rest = hdr.partition("/")
                if not sep:
                    ns = ""
                name, sep2, holder = rest.partition("/")
                if not sep2:
                    name = holder = ""
                # _lease_lock held across check AND commit (86 -> shard
                # 87 -> ring 88): the takeover PATCH serializes on the
                # same lease lock, so an already-validated deposed write
                # can never commit after the handover
                with store._lease_lock:
                    if not (
                        name and holder
                        and store.lease_held(ns, name, holder)
                    ):
                        self._fence_claim = (ns, name, holder)
                        return True, None
                    return False, fn()

            def _send_fencing_409(self) -> None:
                ns, name, holder = self._fence_claim
                self._send_json({
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Failure",
                    "message": (
                        f"fencing lease {ns}/{name} is not held by "
                        f"{holder}"
                    ),
                    "reason": "Conflict", "code": 409,
                }, 409)

            def do_GET(self):  # noqa: N802
                self._admitted(self._do_get)

            def _do_get(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/healthz":
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                    return
                if parsed.path == "/metrics":
                    # overload-protection surface (anonymous, like
                    # /healthz): inflight per band, 429 rejections, watch
                    # terminations — scraped by the watcher-fleet gate
                    adm = server_obj._admission
                    lags, _peak, encodes = store.ring_stats()
                    body = render_apiserver_metrics(
                        adm.inflight if adm else {},
                        adm.rejected if adm else {},
                        store.watch_terminations,
                    ) + render_timing_metrics(
                        timing, lags, encodes, lag_hist=store.lag_hist
                    )
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parsed.path == "/debug/flight":
                    # flight recorder dump (anonymous, like /metrics):
                    # the bounded ring of recent request records — the
                    # engine auto-grabs it on a /readyz degradation edge
                    body = json.dumps(
                        timing.flight_doc("mock"), separators=(",", ":")
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parsed.path == "/debug/watchers":
                    # watch-plane census (anonymous, like /debug/flight):
                    # per-watcher ring-cursor lag, replay backlog, age,
                    # and termination risk — the C10k before-photo
                    body = json.dumps(
                        store.watchers_doc("mock"), separators=(",", ":")
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if not self._authorized():
                    return
                if parsed.path in DISCOVERY:
                    self._send_json(DISCOVERY[parsed.path])
                    return
                if parsed.path == "/snapshot":
                    # the mock's `etcdctl snapshot save`
                    self._send_json(store.dump())
                    return
                lm = _LEASE_PATHS.match(parsed.path)
                if lm:
                    if not lm.group("name"):
                        self.send_error(404)  # no lease LIST in the dialect
                        return
                    code, body = store.lease_get(
                        lm.group("ns"), lm.group("name")
                    )
                    self._send_body(body, code)
                    return
                m = _match_path(parsed.path)
                if not m or m.group("sub") == "binding":
                    self.send_error(404)  # binding is create-only
                    return
                q = urllib.parse.parse_qs(parsed.query)
                kind, ns, name = m.group("kind"), m.group("ns"), m.group("name")
                if m.group("sub") == "log":
                    # ns passed verbatim (no defaulting): a namespace-less
                    # pods/NAME/log matches neither server's store key —
                    # the C++ mirror behaves identically
                    doc, code = self._commit(lambda: pod_log_status(
                        store, ns, name, (q.get("container") or [None])[0]
                    ))
                    self._send_json(doc, code)
                    return
                if name:
                    body = self._commit(
                        lambda: store.get_bytes(kind, ns, name)
                    )
                    if body is None:
                        self._send_json({"kind": "Status", "code": 404}, 404)
                    else:
                        self._send_body(body)
                    return
                fs = (q.get("fieldSelector") or [None])[0]
                ls = (q.get("labelSelector") or [None])[0]
                # request deadline (ListOptions.timeoutSeconds): live on
                # watch streams (clean close at an event boundary when it
                # expires); vacuously honored on LIST — list handlers
                # never queue (admission rejects with 429 instead) and
                # serve synchronously, so the deadline cannot expire
                # mid-request. Non-numeric values are ignored, matching
                # the C++ mirror's atof. Parity-pinned in
                # tests/test_native_apiserver.py.
                try:
                    timeout_s = float(
                        (q.get("timeoutSeconds") or ["0"])[0] or 0
                    )
                except ValueError:
                    timeout_s = 0.0
                if (q.get("watch") or ["false"])[0] in ("true", "1"):
                    self._stream_watch(
                        kind, fs, ls,
                        (q.get("resourceVersion") or [None])[0],
                        (q.get("allowWatchBookmarks") or ["false"])[0]
                        in ("true", "1"),
                        timeout_s,
                    )
                    return
                try:
                    body = self._commit(lambda: store.list_bytes(
                        kind,
                        field_selector=fs,
                        label_selector=ls,
                        limit=int((q.get("limit") or [0])[0] or 0),
                        continue_=(q.get("continue") or [None])[0],
                    ))
                except WatchExpired as e:
                    # expired continue token: 410 Gone, client restarts
                    # the list (kube-apiserver "continue too old" answer)
                    self._send_json(_expired_status(str(e)), 410)
                    return
                except MalformedContinue:
                    self._send_json(
                        {"kind": "Status", "apiVersion": "v1",
                         "status": "Failure",
                         "message": "continue key is not valid",
                         "reason": "BadRequest", "code": 400},
                        400,
                    )
                    return
                self._send_body(body)

            def _stream_watch(
                self, kind, fs, ls, rv, bookmarks=False, timeout_s=0.0
            ):
                try:
                    w = store.watch(
                        kind, field_selector=fs, label_selector=ls,
                        resource_version=rv, allow_bookmarks=bookmarks,
                    )
                except ValueError:
                    # non-numeric resourceVersion: 400, like the real
                    # apiserver (and the C++ mirror)
                    self._send_json(
                        {"kind": "Status", "apiVersion": "v1",
                         "status": "Failure",
                         "message": f"invalid resourceVersion: {rv!r}",
                         "reason": "BadRequest", "code": 400},
                        400,
                    )
                    return
                except TooLargeResourceVersion as e:
                    # a resume AHEAD of the store (server restart reset the
                    # revision clock): the real apiserver fails the watch
                    # handshake with a plain 504 Timeout response carrying
                    # a ResourceVersionTooLarge cause — retry semantics,
                    # not a stream ERROR event
                    self._send_json(_too_large_rv_status(e), 504)
                    return
                except WatchExpired as e:
                    # the real apiserver answers an expired watch resume
                    # with 200 + one ERROR event carrying a 410 Status,
                    # then closes the stream
                    payload = json.dumps(
                        {"type": "ERROR", "object": _expired_status(str(e))},
                        separators=(",", ":"),
                    ).encode() + b"\n"
                    self.close_connection = True
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                # a live watch stream is long-running: no unary phase
                # observation (the handshake errors above stay timed)
                self._t_start = None
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                # wfile is fully buffered (wbufsize): push the headers out
                # now or the client blocks until the first event arrives
                self.wfile.flush()
                deadline = (
                    time.monotonic() + timeout_s if timeout_s > 0 else None
                )
                try:
                    while True:
                        slice_s = None
                        if deadline is not None:
                            slice_s = deadline - time.monotonic()
                            if slice_s <= 0:
                                slice_s = 0.0
                        lines, state = w.take_lines(timeout=slice_s)
                        if state == "timeout":
                            # timeoutSeconds expiry: the real apiserver
                            # ENDS the watch cleanly (terminal chunk) at
                            # an event boundary; the client resumes from
                            # its last revision
                            store.count_termination("deadline")
                            self.wfile.write(b"0\r\n\r\n")
                            self.wfile.flush()
                            break
                        # the whole pending batch leaves in one buffered
                        # write+flush (the ring already paid the one
                        # encode; the lines are shared bytes)
                        for line in lines:
                            self.wfile.write(
                                b"%x\r\n%s\r\n" % (len(line), line)
                            )
                        if lines:
                            self.wfile.flush()
                        if state == "stopped":
                            # stream stopped server-side. A slow-consumer
                            # (ring-lag) termination closes the connection
                            # abruptly (no terminal chunk — the backlog is
                            # already dropped; the client re-lists,
                            # 410-class recovery), same as shutdown/
                            # restore closes.
                            break
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    w.stop()
                self.close_connection = True

            def do_PATCH(self):  # noqa: N802
                self._admitted(self._do_patch)

            def _do_patch(self):
                if not self._authorized():
                    return
                parsed = urllib.parse.urlparse(self.path)
                lm = _LEASE_PATHS.match(parsed.path)
                if lm and lm.group("name"):
                    # PATCH-renew: the leadership plane's heartbeat
                    # (renew / conflict-on-stolen-holder / expiry-acquire
                    # arbitrated server-side under the store lock). A
                    # valid-JSON non-object body reads as an empty spec,
                    # exactly like the C++ twin's non-OBJ tolerance.
                    patch = self._body()
                    if patch is None:
                        # no body at all: the C++ twin's JParser("")
                        # rejection answers 400
                        self._send_json({"kind": "Status", "code": 400}, 400)
                        return
                    spec = (
                        patch.get("spec") if isinstance(patch, dict)
                        else None
                    )
                    code, body = store.lease_renew(
                        lm.group("ns"), lm.group("name"), spec
                    )
                    self._send_body(body, code)
                    return
                m = _match_path(parsed.path)
                if (
                    not m
                    or not m.group("name")
                    or m.group("sub") in ("binding", "log")
                ):
                    self.send_error(404)  # binding create-only, log GET-only
                    return
                kind, ns, name = m.group("kind"), m.group("ns"), m.group("name")
                patch = self._body()
                if m.group("sub") == "status":
                    fenced, body = self._fenced_commit(
                        lambda: self._commit(
                            lambda: store.patch_status_bytes(
                                kind, ns, name, patch
                            )
                        )
                    )
                else:
                    fenced, body = self._fenced_commit(
                        lambda: self._commit(
                            lambda: store.patch_meta_bytes(
                                kind, ns, name, patch
                            )
                        )
                    )
                if fenced:
                    self._send_fencing_409()
                    return
                if body is None:
                    self._send_json({"kind": "Status", "code": 404}, 404)
                else:
                    self._send_body(body)

            def do_DELETE(self):  # noqa: N802
                self._admitted(self._do_delete)

            def _do_delete(self):
                if not self._authorized():
                    return
                parsed = urllib.parse.urlparse(self.path)
                m = _match_path(parsed.path)
                if (
                    not m
                    or not m.group("name")
                    or m.group("sub") in ("binding", "log")
                ):
                    self.send_error(404)  # binding create-only, log GET-only
                    return
                try:
                    body = self._body() or {}
                except _BadBody:
                    # C++ parity: an undecodable DELETE body falls back to
                    # default grace (JParser failure leaves b non-OBJ)
                    body = {}
                grace = body.get("gracePeriodSeconds")
                fenced, _r = self._fenced_commit(
                    lambda: self._commit(lambda: store.delete(
                        m.group("kind"), m.group("ns"), m.group("name"),
                        grace_seconds=None if grace is None else int(grace),
                    ))
                )
                if fenced:
                    self._send_fencing_409()
                    return
                self._send_json({"kind": "Status", "status": "Success"})

            def do_POST(self):  # noqa: N802 (test convenience: create)
                self._admitted(self._do_post)

            def _do_post(self):
                if not self._authorized():
                    return
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/restore":
                    # the mock's `etcdctl snapshot restore` + etcd restart
                    store.load(self._body() or {})
                    self._send_json({"kind": "Status", "status": "Success"})
                    return
                if parsed.path == "/compact":
                    # the mock's `etcdctl compact`: expire the watch cache
                    # and in-flight continue tokens NOW (test/ops hook;
                    # the real apiserver compacts every 5 minutes)
                    self._body()  # drain
                    self._send_json({"compactedRevision": store.compact()})
                    return
                lm = _LEASE_PATHS.match(parsed.path)
                if lm:
                    if lm.group("name"):
                        self.send_error(404)  # create is collection-POST
                        return
                    obj = self._body()
                    if not isinstance(obj, dict):
                        # valid-JSON non-object create: 400, like the
                        # C++ twin's `obj.type != OBJ` rejection
                        self._send_json({"kind": "Status", "code": 400}, 400)
                        return
                    name = (obj.get("metadata") or {}).get("name")
                    if not name or not isinstance(name, str):
                        self._send_json({"kind": "Status", "code": 400}, 400)
                        return
                    code, body = store.lease_create(
                        lm.group("ns"), name, obj.get("spec")
                    )
                    self._send_body(body, code)
                    return
                m = _match_path(parsed.path)
                if not m:
                    self.send_error(404)
                    return
                obj = self._body()
                if m.group("sub") == "binding":
                    # the real scheduler's bind: POST v1 Binding
                    node = ((obj or {}).get("target") or {}).get("name") or ""
                    try:
                        fenced, pod = self._fenced_commit(
                            lambda: self._commit(lambda: store.bind(
                                m.group("ns"), m.group("name"), node
                            ))
                        )
                        if fenced:
                            self._send_fencing_409()
                            return
                    except BindConflict as e:
                        self._send_json(
                            {"kind": "Status", "status": "Failure",
                             "reason": "Conflict", "message": str(e),
                             "code": 409},
                            409,
                        )
                        return
                    if pod is None:
                        self._send_json({"kind": "Status", "code": 404}, 404)
                    else:
                        self._send_json(
                            {"kind": "Status", "status": "Success", "code": 201},
                            201,
                        )
                    return
                if m.group("name") or m.group("sub"):
                    self.send_error(404)
                    return
                if m.group("ns"):
                    obj.setdefault("metadata", {})["namespace"] = m.group("ns")
                try:
                    fenced, body = self._fenced_commit(
                        lambda: self._commit(
                            lambda: store.create_bytes(m.group("kind"), obj)
                        )
                    )
                    if fenced:
                        self._send_fencing_409()
                        return
                except AlreadyExists as e:
                    self._send_json(
                        {"kind": "Status", "apiVersion": "v1",
                         "status": "Failure", "message": str(e),
                         "reason": "AlreadyExists", "code": 409},
                        409,
                    )
                    return
                self._send_body(body, 201)

        return Handler

def load_token_file(path: str) -> frozenset[str]:
    """kube-apiserver --token-auth-file CSV (token,user,uid[,groups]):
    every row is an accepted credential — the real apiserver authenticates
    against the whole file, not just its first line. Blank rows are
    skipped; an empty result means the file is unusable (callers fail
    hard rather than degrade to anonymous)."""
    with open(path) as f:
        return frozenset(
            tok for line in f if (tok := line.strip().split(",", 1)[0])
        )


def main(argv=None) -> int:
    """Standalone mock apiserver: `--port N` then serve forever."""
    import argparse
    import signal

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0)
    p.add_argument(
        "--address",
        default="127.0.0.1",
        help="bind address (0.0.0.0 for containerized clusters with "
        "published ports)",
    )
    p.add_argument(
        "--audit-log",
        default="",
        help="append one audit.k8s.io/v1 Event JSON line per request here",
    )
    p.add_argument(
        "--data-file",
        default="",
        help="persist the store here across restarts (the mock's etcd "
        "data dir): loaded at startup, written on shutdown",
    )
    p.add_argument(
        "--authorization",
        action="store_true",
        help="serve rbac.authorization.k8s.io/v1 with bootstrap policy "
        "(the mock analogue of --authorization-mode=Node,RBAC)",
    )
    p.add_argument(
        "--token-auth-file",
        default="",
        help="CSV token file (token,user,uid[,groups]) as kube-apiserver's "
        "--token-auth-file; requests without the token get 401",
    )
    p.add_argument(
        "--max-inflight", type=int, default=None,
        help="max concurrent LIST/GET requests before answering 429 + "
        "Retry-After (kube-apiserver --max-requests-inflight; 0 = off, "
        "default from KWOK_TPU_MAX_INFLIGHT)",
    )
    p.add_argument(
        "--max-mutating-inflight", type=int, default=None,
        help="max concurrent POST/PATCH/DELETE requests before 429 "
        "(kube-apiserver --max-mutating-requests-inflight; 0 = off, "
        "default from KWOK_TPU_MAX_MUTATING_INFLIGHT)",
    )
    p.add_argument("--tls-cert-file", default="",
                   help="serve HTTPS with this certificate")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument("--client-ca-file", default="",
                   help="require client certificates signed by this CA (mTLS)")
    args = p.parse_args(argv)
    token = None
    if args.token_auth_file:
        token = load_token_file(args.token_auth_file)
        if not token:
            # an unusable token file must fail hard, not degrade to
            # anonymous (the real kube-apiserver refuses to start too)
            print(
                f"token file {args.token_auth_file} has no token",
                flush=True,
            )
            return 1
    srv = HttpFakeApiserver(
        port=args.port,
        address=args.address,
        audit_log_path=args.audit_log or None,
        token=token,
        tls_cert_file=args.tls_cert_file or None,
        tls_key_file=args.tls_private_key_file or None,
        client_ca_file=args.client_ca_file or None,
        max_inflight=args.max_inflight,
        max_mutating_inflight=args.max_mutating_inflight,
    )
    if args.data_file:
        try:
            with open(args.data_file) as f:
                srv.store.load(json.load(f))
            print(f"restored store from {args.data_file}", flush=True)
        except FileNotFoundError:
            pass
    if args.authorization:
        seed_bootstrap_rbac(srv.store)
    print(f"mock apiserver listening on {srv.url}", flush=True)

    # SIGTERM arrives on the thread running serve_forever, so calling
    # shutdown() from the handler would deadlock (it waits for the serve
    # loop it interrupted). Raise instead: the exception unwinds out of
    # serve_forever and the finally block persists the store.
    def _term(*_a):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _term)
    try:
        srv.httpd.serve_forever()
    except (KeyboardInterrupt, SystemExit):
        pass
    finally:
        if args.data_file:
            tmp = args.data_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump(srv.store.dump(), f)
            os.replace(tmp, args.data_file)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
