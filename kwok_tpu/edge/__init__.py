"""The API edge: everything that talks JSON/HTTP to a kube-apiserver.

The device never sees a string; this package converts between Kubernetes
objects and engine rows:

- ingest: watch/list events -> row writes (selector bits, phase, deletion)
- render: dirty rows -> status documents (the reference's templates,
  pkg/kwok/controllers/templates/, as plain dict builders)
- merge: strategic-merge + no-op suppression semantics matching
  configureNode / computePatchData (node_controller.go:356-391,
  pod_controller.go:404-439)
- kubeclient: list/watch/patch transport with re-watch backoff matching
  node_controller.go:241-254
"""

from kwok_tpu.edge.selectors import LabelSelector, parse_selector
from kwok_tpu.edge.ippool import IPPool

__all__ = ["LabelSelector", "parse_selector", "IPPool"]
