"""Kubernetes label-selector parsing and matching.

Replaces the reference's use of k8s.io/apimachinery labels.Parse
(pkg/kwok/controllers/utils.go:207-212, controller.go:90-96). Supports the
full string grammar: `k=v`, `k==v`, `k!=v`, `k in (a,b)`, `k notin (a,b)`,
`k` (exists), `!k` (not exists), comma-joined requirements.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Mapping, Sequence

_IN_RE = re.compile(r"^(?P<key>[^\s!=]+)\s+(?P<op>in|notin)\s+\((?P<vals>[^)]*)\)$")


@dataclasses.dataclass(frozen=True)
class Requirement:
    key: str
    op: str  # "=", "!=", "in", "notin", "exists", "!"
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        if self.op == "exists":
            return present
        if self.op == "!":
            return not present
        if self.op in ("=", "in"):
            return present and labels[self.key] in self.values
        if self.op in ("!=", "notin"):
            # k8s semantics: != / notin match when key is absent too
            return not present or labels[self.key] not in self.values
        raise ValueError(f"unknown op {self.op}")


@dataclasses.dataclass(frozen=True)
class LabelSelector:
    requirements: tuple[Requirement, ...]

    def matches(self, labels: Mapping[str, str] | None) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    @property
    def empty(self) -> bool:
        return not self.requirements


def _split_top_level(s: str) -> Sequence[str]:
    """Split on commas not inside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def parse_selector(s: str | None) -> LabelSelector | None:
    """Parse a selector string; empty/None -> None (matches nothing is the
    caller's decision, mirroring labelsParse returning nil)."""
    if not s or not s.strip():
        return None
    reqs: list[Requirement] = []
    for part in _split_top_level(s.strip()):
        m = _IN_RE.match(part)
        if m:
            vals = tuple(v.strip() for v in m.group("vals").split(",") if v.strip())
            reqs.append(Requirement(m.group("key"), m.group("op"), vals))
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            reqs.append(Requirement(k.strip(), "!=", (v.strip(),)))
            continue
        if "==" in part:
            k, v = part.split("==", 1)
            reqs.append(Requirement(k.strip(), "=", (v.strip(),)))
            continue
        if "=" in part:
            k, v = part.split("=", 1)
            reqs.append(Requirement(k.strip(), "=", (v.strip(),)))
            continue
        if part.startswith("!"):
            reqs.append(Requirement(part[1:].strip(), "!"))
            continue
        reqs.append(Requirement(part, "exists"))
    return LabelSelector(tuple(reqs))
