"""KubeClient protocol: the exact API surface the engine needs.

The reference consumes client-go's typed clientset; the contract it actually
exercises is list / watch / get / patch-status / merge-patch-metadata /
delete (SURVEY.md section 3). Implementations:

- tests/fake_apiserver.FakeKube — in-memory, the unit-test fixture (the
  analogue of fake.NewSimpleClientset in node_controller_test.go:38)
- kwok_tpu.edge.httpclient.HttpKubeClient — real apiserver over HTTP(S)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, Protocol

# Watch event types (k8s wire values).
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
ERROR = "ERROR"


@dataclasses.dataclass(frozen=True)
class WatchEvent:
    type: str
    object: dict


class WatchExpired(Exception):
    """The requested resourceVersion has been compacted away (HTTP 410
    Gone / watch ERROR event with code 410, reason "Expired"). The caller
    must fall back to a full re-list + fresh watch — the client-go
    reflector's ListAndWatch recovery (node_controller.go:241-254 re-watch
    semantics ride on it)."""


class TooLargeResourceVersion(Exception):
    """The requested resourceVersion is AHEAD of the server's store (e.g.
    the server restarted and its revision clock reset). The real apiserver
    answers this with HTTP 504 reason "Timeout", message "Too large
    resource version: X, current: Y", a ResourceVersionTooLarge cause and
    retryAfterSeconds — NOT 410 Expired; client-go retries the same
    revision after the hint instead of re-listing. The engine bounds those
    retries and falls back to a re-list so a permanently-reset server
    can't wedge it."""

    def __init__(self, rv: int, current: int, retry_after: float = 1.0):
        super().__init__(
            f"Too large resource version: {rv}, current: {current}"
        )
        self.rv = int(rv)
        self.current = int(current)
        self.retry_after = float(retry_after)


class ContinueExpired(Exception):
    """A paged LIST's continue token was compacted away mid-scan (HTTP
    410 on the continuation page). Typed so callers can restart their
    scan cleanly — distinguishable from a legitimately-empty final page,
    which also carries no further token but IS a completed scan."""


class TooManyRequests(Exception):
    """HTTP 429: one of the apiserver's max-inflight bands is saturated
    (kube-apiserver --max-requests-inflight /
    --max-mutating-requests-inflight rejection; KEP-1040 semantics).
    Carries the server's Retry-After hint — callers THROTTLE through the
    shared RetryPolicy (sleep at least ``retry_after``) and retry; they
    never hammer, and other HTTP statuses stay non-retryable."""

    def __init__(self, message: str = "Too many requests",
                 retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = float(retry_after)


class WatchHandle(Protocol):
    def __iter__(self) -> Iterator[WatchEvent]: ...
    def stop(self) -> None: ...


class KubeClient(Protocol):
    """kind is the lowercase plural resource name: "nodes" | "pods"."""

    def list(
        self,
        kind: str,
        *,
        field_selector: str | None = None,
        label_selector: str | None = None,
    ) -> list[dict]: ...

    def watch(
        self,
        kind: str,
        *,
        field_selector: str | None = None,
        label_selector: str | None = None,
        resource_version: int | str | None = None,
        allow_bookmarks: bool = False,
    ) -> WatchHandle:
        """resource_version > 0 resumes the stream strictly after that
        revision (the server replays its watch cache); raises WatchExpired
        — or the stream yields an ERROR event with code 410 — when the
        revision has been compacted away. allow_bookmarks opts into
        periodic BOOKMARK events (objects carrying only
        metadata.resourceVersion) so a quiet stream's resume revision
        keeps advancing past compactions — client-go's reflector always
        opts in; so does the engine."""
        ...

    def get(self, kind: str, namespace: str | None, name: str) -> dict | None: ...

    def patch_status(
        self, kind: str, namespace: str | None, name: str, patch: dict
    ) -> dict | None:
        """Strategic-merge patch of the status subresource
        (PatchStatus / Patch ..., "status" in the reference)."""
        ...

    def patch_meta(
        self, kind: str, namespace: str | None, name: str, patch: dict
    ) -> dict | None:
        """JSON merge patch of the main resource (finalizer strip,
        pod_controller.go:45)."""
        ...

    def delete(
        self, kind: str, namespace: str | None, name: str, grace_seconds: int = 0
    ) -> None: ...


def obj_key(obj: dict) -> tuple[str, str]:
    meta = obj.get("metadata") or {}
    return (meta.get("namespace") or "", meta.get("name") or "")


def match_field_selector(obj: dict, field_selector: str | None) -> bool:
    """Minimal fieldSelector support: the forms the engine uses
    (spec.nodeName!=VALUE / spec.nodeName=VALUE, comma-joined;
    pod_controller.go:47, :373)."""
    if not field_selector:
        return True
    for term in field_selector.split(","):
        term = term.strip()
        if not term:
            continue
        if "!=" in term:
            path, val = term.split("!=", 1)
            if _field(obj, path) == val:
                return False
        elif "=" in term:
            path, val = term.split("==" if "==" in term else "=", 1)
            if _field(obj, path.rstrip("=")) != val:
                return False
    return True


def _field(obj: dict, path: str) -> str:
    cur: Any = obj
    for part in path.strip().split("."):
        if not isinstance(cur, dict):
            return ""
        cur = cur.get(part)
    return "" if cur is None else str(cur)
