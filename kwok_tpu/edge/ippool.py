"""Pod-IP allocation from a CIDR: base + index arithmetic.

The reference's ipPool (pkg/kwok/controllers/utils.go:37-117) hands out
sequential IPs with a recycled free-list and a `Use` method to pin IPs that
existed before startup. Same contract here, with integer arithmetic on the
network base address.
"""

from __future__ import annotations

import ipaddress
import threading


class IPPool:
    """Thread-safe: get/put/use are called from patch-executor workers."""

    def __init__(self, cidr: str) -> None:
        self.net = ipaddress.ip_network(cidr, strict=False)
        self._base = int(self.net.network_address)
        self._next = 1  # skip the network address, like addIP starting at offset
        self._free: list[str] = []
        self._used: set[str] = set()
        self._lock = threading.Lock()

    def contains(self, ip: str) -> bool:
        try:
            return ipaddress.ip_address(ip) in self.net
        except ValueError:
            return False

    def get(self) -> str:
        with self._lock:
            while self._free:
                ip = self._free.pop()
                if ip not in self._used:
                    self._used.add(ip)
                    return ip
            while True:
                ip = str(ipaddress.ip_address(self._base + self._next))
                self._next += 1
                if ip not in self._used:
                    self._used.add(ip)
                    return ip

    def put(self, ip: str) -> None:
        """Recycle an IP (pod Deleted event, pod_controller.go:334-337).
        Out-of-CIDR IPs are rejected like the reference's Put."""
        if not self.contains(ip):
            return
        with self._lock:
            if ip in self._used:
                self._used.discard(ip)
                self._free.append(ip)

    def use(self, ip: str) -> None:
        """Pin an IP observed in a pre-existing pod status
        (pod_controller.go:381-385). Out-of-CIDR IPs are ignored."""
        if self.contains(ip):
            with self._lock:
                self._used.add(ip)
