"""Pod-IP allocation from a CIDR: base + index arithmetic.

The reference's ipPool (pkg/kwok/controllers/utils.go:37-117) hands out
sequential IPs with a recycled free-list and a `Use` method to pin IPs that
existed before startup. Same contract here, with integer arithmetic on the
network base address.
"""

from __future__ import annotations

import ipaddress
import threading


_DIGITS = frozenset("0123456789")


def _ip4_int(ip: str) -> int | None:
    """CANONICAL dotted-quad -> int without an ipaddress object (the
    allocator runs once per pod; IPv4Address construction dominated it in
    profiles). Only canonical quads qualify — no leading zeros, ASCII
    decimal digits only (str.isdigit accepts non-decimal digit chars that
    int() rejects) — everything else falls back to the ipaddress parser so
    behavior matches it exactly."""
    parts = ip.split(".")
    if len(parts) != 4:
        return None
    v = 0
    for p in parts:
        if not 0 < len(p) <= 3 or (len(p) > 1 and p[0] == "0"):
            return None
        for c in p:
            if c not in _DIGITS:
                return None
        o = int(p)
        if o > 255:
            return None
        v = (v << 8) | o
    return v


def _ip4_str(v: int) -> str:
    return f"{v >> 24 & 255}.{v >> 16 & 255}.{v >> 8 & 255}.{v & 255}"


class IPPool:
    """Thread-safe: get/put/use are called from patch-executor workers."""

    def __init__(self, cidr: str) -> None:
        self.net = ipaddress.ip_network(cidr, strict=False)
        self._base = int(self.net.network_address)
        self._v4 = self.net.version == 4
        self._mask = int(self.net.netmask) if self._v4 else 0
        self._next = 1  # skip the network address, like addIP starting at offset
        self._free: list[str] = []
        self._used: set[str] = set()
        self._lock = threading.Lock()

    def contains(self, ip: str) -> bool:
        if self._v4:
            v = _ip4_int(ip)
            if v is not None:
                return (v & self._mask) == self._base
        try:
            return ipaddress.ip_address(ip) in self.net
        except ValueError:
            return False

    def get(self) -> str:
        with self._lock:
            while self._free:
                ip = self._free.pop()
                if ip not in self._used:
                    self._used.add(ip)
                    return ip
            while True:
                v = self._base + self._next
                ip = _ip4_str(v) if self._v4 else str(ipaddress.ip_address(v))
                self._next += 1
                if ip not in self._used:
                    self._used.add(ip)
                    return ip

    def get_many(self, n: int) -> list[str]:
        """Batch get(): one lock hold for n allocations — the native emit
        gather's bulk first-transition shape (ISSUE 14), where a per-row
        get() was 40k lock operations per 20k-pod batch."""
        out: list[str] = []
        with self._lock:
            free = self._free
            used = self._used
            while free and len(out) < n:
                ip = free.pop()
                if ip not in used:
                    used.add(ip)
                    out.append(ip)
            while len(out) < n:
                v = self._base + self._next
                ip = _ip4_str(v) if self._v4 else str(ipaddress.ip_address(v))
                self._next += 1
                if ip not in used:
                    used.add(ip)
                    out.append(ip)
        return out

    def put(self, ip: str) -> None:
        """Recycle an IP (pod Deleted event, pod_controller.go:334-337).
        Out-of-CIDR IPs are rejected like the reference's Put."""
        if not self.contains(ip):
            return
        with self._lock:
            if ip in self._used:
                self._used.discard(ip)
                self._free.append(ip)

    def use(self, ip: str) -> None:
        """Pin an IP observed in a pre-existing pod status
        (pod_controller.go:381-385). Out-of-CIDR IPs are ignored."""
        if self.contains(ip):
            with self._lock:
                self._used.add(ip)
