"""Pod-IP allocation from a CIDR: base + index arithmetic.

The reference's ipPool (pkg/kwok/controllers/utils.go:37-117) hands out
sequential IPs with a recycled free-list and a `Use` method to pin IPs that
existed before startup. Same contract here, with integer arithmetic on the
network base address.
"""

from __future__ import annotations

import ipaddress
import threading


_DIGITS = frozenset("0123456789")


def _ip4_int(ip: str) -> int | None:
    """CANONICAL dotted-quad -> int without an ipaddress object (the
    allocator runs once per pod; IPv4Address construction dominated it in
    profiles). Only canonical quads qualify — no leading zeros, ASCII
    decimal digits only (str.isdigit accepts non-decimal digit chars that
    int() rejects) — everything else falls back to the ipaddress parser so
    behavior matches it exactly."""
    parts = ip.split(".")
    if len(parts) != 4:
        return None
    v = 0
    for p in parts:
        if not 0 < len(p) <= 3 or (len(p) > 1 and p[0] == "0"):
            return None
        for c in p:
            if c not in _DIGITS:
                return None
        o = int(p)
        if o > 255:
            return None
        v = (v << 8) | o
    return v


def _ip4_str(v: int) -> str:
    return f"{v >> 24 & 255}.{v >> 16 & 255}.{v >> 8 & 255}.{v & 255}"


class IPPool:
    """Thread-safe: get/put/use are called from patch-executor workers."""

    def __init__(self, cidr: str) -> None:
        self.net = ipaddress.ip_network(cidr, strict=False)
        self._base = int(self.net.network_address)
        self._v4 = self.net.version == 4
        self._mask = int(self.net.netmask) if self._v4 else 0
        self._next = 1  # skip the network address, like addIP starting at offset
        self._lane: tuple[int, int, int] | None = None  # (index, n, span)
        self._lane_j = 0
        self._free: list[str] = []
        self._used: set[str] = set()
        self._lock = threading.Lock()

    def _next_off(self) -> int:
        """Next allocation offset (callers hold ``_lock``). Unpartitioned:
        the classic unbounded sequential walk. Partitioned (process lanes):
        lane ``index`` owns the ``index``-th span-sized slice of every
        ``n*span`` super-block — disjoint across lanes for ANY allocation
        count (a lane that outgrows its in-CIDR slice jumps to its slice
        of the next super-block instead of walking into a neighbor's),
        while staying unbounded exactly like the base walk."""
        lane = self._lane
        if lane is None:
            off = self._next
            self._next += 1
            return off
        index, n, span = lane
        j = self._lane_j
        self._lane_j = j + 1
        return 1 + index * span + (j // span) * (n * span) + (j % span)

    def contains(self, ip: str) -> bool:
        if self._v4:
            v = _ip4_int(ip)
            if v is not None:
                return (v & self._mask) == self._base
        try:
            return ipaddress.ip_address(ip) in self.net
        except ValueError:
            return False

    def get(self) -> str:
        with self._lock:
            while self._free:
                ip = self._free.pop()
                if ip not in self._used:
                    self._used.add(ip)
                    return ip
            while True:
                v = self._base + self._next_off()
                ip = _ip4_str(v) if self._v4 else str(ipaddress.ip_address(v))
                if ip not in self._used:
                    self._used.add(ip)
                    return ip

    def get_many(self, n: int) -> list[str]:
        """Batch get(): one lock hold for n allocations — the native emit
        gather's bulk first-transition shape (ISSUE 14), where a per-row
        get() was 40k lock operations per 20k-pod batch."""
        out: list[str] = []
        with self._lock:
            free = self._free
            used = self._used
            while free and len(out) < n:
                ip = free.pop()
                if ip not in used:
                    used.add(ip)
                    out.append(ip)
            while len(out) < n:
                v = self._base + self._next_off()
                ip = _ip4_str(v) if self._v4 else str(ipaddress.ip_address(v))
                if ip not in used:
                    used.add(ip)
                    out.append(ip)
        return out

    def partition_lanes(self, index: int, n: int) -> None:
        """Restrict this pool to the ``index``-th of ``n`` disjoint
        allocation sequences (process lanes, engine/proclanes.py): each
        lane process allocates from its own slice of every span-sized
        super-block (see ``_next_off``), so pods never collide on a
        podIP across lanes — for ANY per-lane allocation count — with
        no cross-process allocator lock, and a respawned lane re-derives
        the same sequence deterministically. ``use``/``put`` still
        accept any in-CIDR IP (re-listed pods may pin IPs allocated
        before a repartition or by another owner). No-op for n <= 1."""
        if n <= 1:
            return
        span = max(1, (self.net.num_addresses - 1) // n)
        with self._lock:
            self._lane = (index, n, span)
            self._lane_j = 0

    def put(self, ip: str) -> None:
        """Recycle an IP (pod Deleted event, pod_controller.go:334-337).
        Out-of-CIDR IPs are rejected like the reference's Put."""
        if not self.contains(ip):
            return
        with self._lock:
            if ip in self._used:
                self._used.discard(ip)
                self._free.append(ip)

    def use(self, ip: str) -> None:
        """Pin an IP observed in a pre-existing pod status
        (pod_controller.go:381-385). Out-of-CIDR IPs are ignored."""
        if self.contains(ip):
            with self._lock:
                self._used.add(ip)
