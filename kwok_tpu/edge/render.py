"""Status-document renderers: dirty rows -> Kubernetes status dicts.

The behavior of the reference's three templates, as plain dict builders
(pkg/kwok/controllers/templates/node.status.tpl, node.heartbeat.tpl,
pod.status.tpl). Rendering happens host-side ONLY for rows the tick kernel
marked dirty — the replacement for per-object template execution
(renderer.go:49-89).

Generalization beyond the reference: phase names and condition bits come
from the row (kwok_tpu.models.lifecycle), so custom rule sets render
faithfully; container states follow the pod phase (running / terminated).
"""

from __future__ import annotations

import datetime
import json
from typing import Any, Mapping

from kwok_tpu.models.lifecycle import NODE_PHASES, POD_PHASES, PhaseSpace

# Default simulated capacity (node.status.tpl:38-50).
DEFAULT_CAPACITY = {"cpu": "1k", "memory": "1Ti", "pods": "1M"}

_NODE_CONDITION_META = {
    "Ready": ("KubeletReady", "kubelet is posting ready status"),
    "OutOfDisk": ("KubeletHasSufficientDisk", "kubelet has sufficient disk space available"),
    "MemoryPressure": ("KubeletHasSufficientMemory", "kubelet has sufficient memory available"),
    "DiskPressure": ("KubeletHasNoDiskPressure", "kubelet has no disk pressure"),
    "NetworkUnavailable": ("RouteCreated", "RouteController created a route"),
    "PIDPressure": ("KubeletHasSufficientPID", "kubelet has sufficient PID available"),
}

_NODE_INFO_DEFAULTS = {
    "architecture": "amd64",
    "bootID": "",
    "containerRuntimeVersion": "",
    "kernelVersion": "",
    "kubeProxyVersion": "fake",
    "kubeletVersion": "fake",
    "machineID": "",
    "operatingSystem": "linux",
    "osImage": "",
    "systemUUID": "",
}


def rfc3339(t: datetime.datetime | str | None) -> str:
    if isinstance(t, str):
        return t
    if t is None:
        t = datetime.datetime.now(datetime.timezone.utc)
    return t.astimezone(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def now_rfc3339() -> str:
    return rfc3339(None)


def parse_rfc3339(ts: str) -> float:
    """RFC3339 timestamp -> unix seconds (inverse of rfc3339; tolerates
    fractional seconds and explicit offsets from real apiservers)."""
    return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()


def _cond_status(cond_bits: int, space: PhaseSpace, name: str) -> str:
    return "True" if (cond_bits >> space.condition_bit(name)) & 1 else "False"


def node_conditions(
    cond_bits: int,
    now: str,
    start_time: str,
    conditions: tuple[str, ...] = NODE_PHASES.conditions,
) -> list[dict]:
    out = []
    for name in conditions:
        reason, message = _NODE_CONDITION_META.get(name, ("KwokRule", name))
        out.append(
            {
                "lastHeartbeatTime": now,
                "lastTransitionTime": start_time,
                "message": message,
                "reason": reason,
                "status": _cond_status(cond_bits, NODE_PHASES, name),
                "type": name,
            }
        )
    return out


def render_node_status(
    node: Mapping[str, Any],
    cond_bits: int,
    node_ip: str,
    now: str,
    start_time: str,
) -> dict:
    """node.status.tpl behavior: defaults fill only absent fields; the
    condition set is always (re)asserted."""
    status = node.get("status") or {}
    rendered: dict[str, Any] = {
        "addresses": status.get("addresses")
        or [{"address": node_ip, "type": "InternalIP"}],
        "allocatable": status.get("allocatable") or dict(DEFAULT_CAPACITY),
        "capacity": status.get("capacity") or dict(DEFAULT_CAPACITY),
        "phase": "Running",
    }
    if status.get("nodeInfo") is not None:
        info = dict(status["nodeInfo"])
        rendered["nodeInfo"] = {
            k: info.get(k) or d for k, d in _NODE_INFO_DEFAULTS.items()
        }
    rendered["conditions"] = node_conditions(cond_bits, now, start_time)
    return rendered


def render_node_heartbeat(cond_bits: int, now: str, start_time: str) -> dict:
    """node.heartbeat.tpl behavior: refresh lastHeartbeatTime on the
    condition set (always patched, no diff check —
    configureHeartbeatNode node_controller.go:393-401)."""
    return {"conditions": node_conditions(cond_bits, now, start_time)}


def _container_state(phase_name: str, start_time: str) -> dict:
    if phase_name in ("Succeeded",):
        return {
            "terminated": {
                "exitCode": 0,
                "finishedAt": start_time,
                "reason": "Completed",
                "startedAt": start_time,
            }
        }
    if phase_name in ("Failed",):
        return {
            "terminated": {
                "exitCode": 1,
                "finishedAt": start_time,
                "reason": "Error",
                "startedAt": start_time,
            }
        }
    return {"running": {"startedAt": start_time}}


def render_pod_status(
    pod: Mapping[str, Any],
    phase_name: str,
    cond_bits: int,
    node_ip: str,
    pod_ip: str,
) -> dict:
    """pod.status.tpl behavior, generalized over the row's phase.

    lastTransitionTime / startTime anchor to metadata.creationTimestamp as
    the template does (pod.status.tpl:1 `$startTime := .metadata.creationTimestamp`).
    """
    meta = pod.get("metadata") or {}
    spec = pod.get("spec") or {}
    status = pod.get("status") or {}
    start_time = meta.get("creationTimestamp") or now_rfc3339()
    ready = phase_name == "Running"

    conditions = []
    for name in ("Initialized", "Ready", "ContainersReady"):
        conditions.append(
            {
                "lastTransitionTime": start_time,
                "status": _cond_status(cond_bits, POD_PHASES, name),
                "type": name,
            }
        )
    for gate in spec.get("readinessGates") or []:
        conditions.append(
            {
                "lastTransitionTime": start_time,
                "status": "True",
                "type": gate.get("conditionType"),
            }
        )

    container_statuses = [
        {
            "image": c.get("image"),
            "name": c.get("name"),
            "ready": ready,
            "restartCount": 0,
            "state": _container_state(phase_name, start_time),
        }
        for c in spec.get("containers") or []
    ]
    init_statuses = [
        {
            "image": c.get("image"),
            "name": c.get("name"),
            "ready": True,
            "restartCount": 0,
            "state": {
                "terminated": {
                    "exitCode": 0,
                    "finishedAt": start_time,
                    "reason": "Completed",
                    "startedAt": start_time,
                }
            },
        }
        for c in spec.get("initContainers") or []
    ]

    return {
        "conditions": conditions,
        "containerStatuses": container_statuses,
        "initContainerStatuses": init_statuses,
        "hostIP": status.get("hostIP") or node_ip,
        "podIP": status.get("podIP") or pod_ip,
        "phase": phase_name,
        "startTime": start_time,
    }


# --- byte oracles (ISSUE 14) ------------------------------------------------
# Canonical patch-body BYTES for the native emit paths' byte-identity
# oracles (tests/test_native_emit.py). Key order above is the wire order
# the codec emits; ensure_ascii=False matches its raw-UTF-8 escaping, so
# for bodies without the exotic control chars json encodes as \b / \f the
# comparison is byte-exact, not merely semantic.


def render_pod_status_body(
    pod: Mapping[str, Any],
    phase_name: str,
    cond_bits: int,
    node_ip: str,
    pod_ip: str,
) -> bytes:
    return json.dumps(
        {"status": render_pod_status(pod, phase_name, cond_bits, node_ip, pod_ip)},
        separators=(",", ":"), ensure_ascii=False,
    ).encode()


def render_heartbeat_body(cond_bits: int, now: str, start_time: str) -> bytes:
    return json.dumps(
        {"status": render_node_heartbeat(cond_bits, now, start_time)},
        separators=(",", ":"), ensure_ascii=False,
    ).encode()
