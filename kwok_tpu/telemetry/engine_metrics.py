"""Named metric handles for the engine + the legacy flat-dict view.

``EngineTelemetry`` owns one ``MetricsRegistry`` slice (optionally shared
across federation members, each under its own ``shard`` label) and one
``Tracer``. The engine writes through typed handles (counter children,
histogram children) — no name lookup, no global lock on the hot path — and
``legacy_dict()`` reconstructs the pre-telemetry ``engine.metrics`` dict
(flat ``*_sum`` floats and last-tick gauges) that tests, the cost model,
and older tooling still read.
"""

from __future__ import annotations

from kwok_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
)
from kwok_tpu.telemetry.trace import Tracer

# Tick stages: every histogram child is pre-created so exposition is stable
# from the first scrape and observe never takes the family lock.
STAGES = ("flush", "kernel", "emit", "drain", "parse")

_HELP = {
    "kwok_transitions_total": "Lifecycle phase transitions applied by the tick kernel",
    "kwok_status_patches_total": "Status patches sent to the apiserver",
    "kwok_heartbeats_total": "Node heartbeat patches sent",
    "kwok_deletes_total": "Pod deletes issued",
    "kwok_epoch_rebases_total": "f32 time-epoch rebases performed",
    "kwok_watch_events_total": "Watch events ingested",
    "kwok_watch_bookmarks_total": "BOOKMARK events consumed (rv advanced, no ingest)",
    "kwok_watch_relists_total": "Full re-lists performed by the watch loops",
    "kwok_patch_errors_total": "Patch/delete jobs that raised",
    "kwok_dropped_jobs_total": "Patch jobs rejected during shutdown",
    "kwok_ticks_total": "Engine ticks executed",
    "kwok_pump_requests_total": "Requests shipped through the native pump",
    "kwok_emit_native_total": "Pod status patches rendered through the "
    "AOT-template native emit path (compiled byte-template splice; the "
    "slow path's per-object renders do not count here)",
    "kwok_emit_slab_bytes_total": "Patch-body bytes spliced into native "
    "emit slabs (divide by kwok_emit_native_total for mean body size)",
    "kwok_tick_seconds": "Wall seconds per engine tick (dispatch + consume halves)",
    "kwok_tick_stage_seconds": "Per-tick wall seconds by stage "
    "(flush=staged-write flush, kernel=device wire wait, emit=patch-job "
    "fan-out, drain=ingest drain, parse=batched C++ line parse)",
    "kwok_pump_send_seconds": "Wall seconds per native pump batch send",
    "kwok_patch_rtt_seconds": "Apiserver round-trip seconds per patch/delete, by path",
    "kwok_watch_lag_seconds": "Enqueue-to-processing delay of drained watch events",
    "kwok_tick_seconds_last": "Duration of the most recent tick",
    "kwok_watch_lag_seconds_last": "Slowest event lag observed in the last drain window",
    "kwok_ingest_queue_depth": "Watch events waiting to be ingested",
    "kwok_tick_inflight": "Device ticks dispatched but not yet consumed",
    "kwok_nodes_managed": "Nodes currently managed",
    "kwok_pods_managed": "Pods currently tracked",
    "kwok_build_info": "Build/version info (value is always 1)",
    "kwok_trace_spans_total": "Spans recorded into the trace ring",
    "kwok_lane_stage_seconds": "Per-lane wall seconds by stage for the "
    "sharded drain+emit pipeline (shard=lane index; drain=ingest apply, "
    "emit=patch fan-out; the router's batched parse stays in the "
    "unlabeled kwok_tick_stage_seconds{stage=parse})",
    "kwok_lane_queue_depth": "Routed events waiting in a lane's ingest "
    "queue (shard=lane index)",
    "kwok_route_batch_seconds": "Wall seconds per native pre-partitioned "
    "route handoff (the router's per-batch lane enqueue; the C parse that "
    "computed the partition stays in kwok_tick_stage_seconds{stage=parse})",
    "kwok_route_partition_events_total": "Events routed to each lane via "
    "the native pre-partitioned parse (shard=lane index; per-event Python "
    "routing does not count here — compare with kwok_watch_events_total "
    "to see the fast-path share)",
    "kwok_rv_rewinds_total": "Store-restore signatures detected: an "
    "object re-listed BELOW its last-ingested resourceVersion (POST "
    "/restore or a blackout recovery from an old snapshot — an object's "
    "own rv can never legitimately decrease); each one resyncs every "
    "watch stream",
    "kwok_restart_recovery_seconds": "Seconds from engine start to the "
    "startup catch-up gate closing (first full re-list of both kinds "
    "ingested + checkpoint reconcile applied); /readyz answers 503 with "
    "reason startup_resync until then",
    "kwok_checkpoint_write_seconds": "Wall seconds serializing + "
    "atomically renaming one crash-durability checkpoint "
    "(resilience/checkpoint.py; only moves with --checkpoint-dir set)",
    "kwok_checkpoint_rows": "Rows in the most recent checkpoint by "
    "state (armed = a Stage delay in flight whose residue the next "
    "restart resumes; idle = no pending rule timer)",
    "kwok_client_throttle_seconds_total": "Cumulative seconds this engine "
    "slept honoring apiserver 429 Retry-After hints (watch/list "
    "reconnects and patch-executor retries); a nonzero rate means the "
    "apiserver's max-inflight bands are saturated and the engine is "
    "backing off instead of hammering",
    "kwok_watch_integrity_resyncs_total": "Full list+RESYNC passes "
    "scheduled because corrupt wire input (an unparseable watch line) "
    "cast doubt on stream completeness; bounded to one per 5s so a "
    "garbling storm cannot LIST-storm the apiserver",
}

# legacy counter name -> (family name, has kind label)
_COUNTERS = {
    "transitions_total": ("kwok_transitions_total", True),
    "status_patches_total": ("kwok_status_patches_total", False),
    "heartbeats_total": ("kwok_heartbeats_total", False),
    "deletes_total": ("kwok_deletes_total", False),
    "epoch_rebases_total": ("kwok_epoch_rebases_total", False),
    "watch_events_total": ("kwok_watch_events_total", True),
    "watch_bookmarks_total": ("kwok_watch_bookmarks_total", False),
    "watch_relists_total": ("kwok_watch_relists_total", False),
    "patch_errors_total": ("kwok_patch_errors_total", False),
    "dropped_jobs_total": ("kwok_dropped_jobs_total", False),
    "ticks_total": ("kwok_ticks_total", False),
    "pump_requests_total": ("kwok_pump_requests_total", False),
    "emit_native_total": ("kwok_emit_native_total", False),
    "emit_slab_bytes_total": ("kwok_emit_slab_bytes_total", False),
    "rv_rewinds_total": ("kwok_rv_rewinds_total", False),
    "watch_integrity_resyncs_total": (
        "kwok_watch_integrity_resyncs_total", False,
    ),
}

_GAUGES = {
    "tick_seconds_last": "kwok_tick_seconds_last",
    "watch_lag_seconds": "kwok_watch_lag_seconds_last",
    "ingest_queue_depth": "kwok_ingest_queue_depth",
    "tick_inflight": "kwok_tick_inflight",
    "nodes_managed": "kwok_nodes_managed",
    "pods_managed": "kwok_pods_managed",
    "restart_recovery_seconds": "kwok_restart_recovery_seconds",
}

_KINDS = ("nodes", "pods")


def register_build_info(registry: MetricsRegistry) -> None:
    """kwok_build_info{version=...} 1 — registered once per registry
    (idempotent: federation members share one)."""
    import platform

    from kwok_tpu import __version__

    fam = registry.gauge(
        "kwok_build_info", _HELP["kwok_build_info"], ("version", "python")
    )
    fam.labels(version=__version__, python=platform.python_version()).set(1)


class EngineTelemetry:
    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        shard: str | None = None,
        tracer: Tracer | None = None,
        trace_capacity: int = 65536,
    ):
        self.registry = registry or MetricsRegistry()
        self.shard = shard
        self.tracer = tracer if tracer is not None else Tracer(trace_capacity)
        r = self.registry
        base = ("shard",) if shard is not None else ()
        sl = {"shard": shard} if shard is not None else {}

        def child(fam):
            return fam.labels(**sl) if shard is not None else fam.child

        self._counters = {}
        self._kind_counters = {}
        for legacy, (name, by_kind) in _COUNTERS.items():
            if by_kind:
                fam = r.counter(name, _HELP[name], base + ("kind",))
                self._kind_counters[legacy] = {
                    k: fam.labels(**sl, kind=k) for k in _KINDS
                }
            else:
                self._counters[legacy] = child(
                    r.counter(name, _HELP[name], base)
                )
        self._gauges = {
            legacy: child(r.gauge(name, _HELP[name], base))
            for legacy, name in _GAUGES.items()
        }
        self.tick_hist = child(
            r.histogram("kwok_tick_seconds", _HELP["kwok_tick_seconds"], base)
        )
        stage_fam = r.histogram(
            "kwok_tick_stage_seconds",
            _HELP["kwok_tick_stage_seconds"],
            base + ("stage",),
        )
        self.stage_hists = {
            s: stage_fam.labels(**sl, stage=s) for s in STAGES
        }
        self.pump_hist = child(
            r.histogram(
                "kwok_pump_send_seconds", _HELP["kwok_pump_send_seconds"], base
            )
        )
        self.route_batch_hist = child(
            r.histogram(
                "kwok_route_batch_seconds",
                _HELP["kwok_route_batch_seconds"],
                base,
            )
        )
        # crash-durability checkpoint surface (resilience/checkpoint.py):
        # pre-created so exposition is stable whether or not a
        # --checkpoint-dir is configured
        self.ckpt_write_hist = child(
            r.histogram(
                "kwok_checkpoint_write_seconds",
                _HELP["kwok_checkpoint_write_seconds"],
                base,
            )
        )
        ckpt_rows_fam = r.gauge(
            "kwok_checkpoint_rows", _HELP["kwok_checkpoint_rows"],
            base + ("state",),
        )
        self.ckpt_rows = {
            s: ckpt_rows_fam.labels(**sl, state=s)
            for s in ("armed", "idle")
        }
        self._rtt_fam = r.histogram(
            "kwok_patch_rtt_seconds",
            _HELP["kwok_patch_rtt_seconds"],
            base + ("path",),
        )
        self._rtt_labels = sl
        self._rtt_children: dict[str, object] = {}
        self.lag_hist = child(
            r.histogram(
                "kwok_watch_lag_seconds",
                _HELP["kwok_watch_lag_seconds"],
                base,
                buckets=DEFAULT_LATENCY_BUCKETS,
            )
        )
        self._spans = child(
            r.counter(
                "kwok_trace_spans_total", _HELP["kwok_trace_spans_total"], base
            )
        )
        # client-side overload accounting: seconds slept honoring 429
        # Retry-After hints (a float counter; monotonic)
        self._throttle = child(
            r.counter(
                "kwok_client_throttle_seconds_total",
                _HELP["kwok_client_throttle_seconds_total"],
                base,
            )
        )
        register_build_info(r)

    # ------------------------------------------------------------- writes

    def inc(self, name: str, v=1) -> None:
        c = self._counters.get(name)
        if c is not None:
            c.inc(v)
        else:
            # kind-labeled family incremented without a kind (legacy call
            # sites that lost the context): attribute to pods, the dominant
            # kind — only the SyncEngine test path reaches this
            self._kind_counters[name]["pods"].inc(v)

    def inc_kind(self, name: str, kind: str, v=1) -> None:
        self._kind_counters[name][kind].inc(v)

    def set_gauge(self, name: str, v) -> None:
        self._gauges[name].set(v)

    def observe_tick(self, seconds: float) -> None:
        self.tick_hist.observe(seconds)
        self._gauges["tick_seconds_last"].set(seconds)

    def observe_stage(self, stage: str, seconds: float) -> None:
        self.stage_hists[stage].observe(seconds)

    def observe_route_batch(self, seconds: float) -> None:
        self.route_batch_hist.observe(seconds)

    def observe_watch_lag(self, seconds: float) -> None:
        self.lag_hist.observe(seconds)
        self._gauges["watch_lag_seconds"].set(seconds)

    def observe_patch_rtt(self, path: str, seconds: float) -> None:
        c = self._rtt_children.get(path)
        if c is None:
            c = self._rtt_fam.labels(**self._rtt_labels, path=path)
            self._rtt_children[path] = c
        c.observe(seconds)

    def add_throttle(self, seconds: float) -> None:
        self._throttle.inc(seconds)

    @property
    def client_throttle_seconds(self) -> float:
        return self._throttle.value

    def span(self, name, t0, t1, lane="drain", args=None) -> None:
        self.tracer.span(name, t0, t1, lane, args)
        self._spans.inc()

    def lane(self, lane_id: str) -> "LaneTelemetry":
        """A per-lane slice for the sharded drain+emit pipeline: lane
        stage observations land BOTH in the lane-labeled
        ``kwok_lane_stage_seconds{shard=...}`` family and in the engine's
        aggregate ``kwok_tick_stage_seconds`` (so the legacy flat view and
        the cost model keep seeing whole-engine totals)."""
        return LaneTelemetry(self, lane_id)

    # ------------------------------------------------------------- reads

    @property
    def ticks_total(self) -> int:
        return self._counters["ticks_total"].value

    @property
    def dropped_jobs_total(self) -> int:
        return self._counters["dropped_jobs_total"].value

    def legacy_dict(self) -> dict:
        """The pre-telemetry ``engine.metrics`` surface: flat names, plain
        numbers. ``*_seconds_sum`` keys come from histogram sums, so the
        old cost-model arithmetic keeps working unchanged."""
        d = {name: c.value for name, c in self._counters.items()}
        for name, by_kind in self._kind_counters.items():
            d[name] = sum(c.value for c in by_kind.values())
        for name, g in self._gauges.items():
            d[name] = g.value
        d["tick_seconds_sum"] = self.tick_hist.sum
        d["tick_flush_seconds_sum"] = self.stage_hists["flush"].sum
        d["tick_kernel_seconds_sum"] = self.stage_hists["kernel"].sum
        d["tick_emit_seconds_sum"] = self.stage_hists["emit"].sum
        d["ingest_drain_seconds_sum"] = self.stage_hists["drain"].sum
        d["ingest_parse_seconds_sum"] = self.stage_hists["parse"].sum
        d["pump_send_seconds_sum"] = self.pump_hist.sum
        return d


# Lane stages: the subset of STAGES a ShardLane runs (flush/kernel stay on
# the coordinator tick thread, parse on the router; both remain unlabeled).
LANE_STAGES = ("drain", "emit")


class LaneTelemetry:
    """Per-lane metric handles for the sharded host pipeline.

    One instance per ShardLane, sharing the engine's registry. The lane
    label intentionally reuses the ``shard`` label name the federation
    surface established, under a lane-specific family — a federated member
    never runs lanes (members are forced single-lane), so the two uses of
    the label cannot collide on one registry.
    """

    def __init__(self, parent: EngineTelemetry, lane_id: str):
        self.parent = parent
        self.lane_id = str(lane_id)
        r = parent.registry
        fam = r.histogram(
            "kwok_lane_stage_seconds",
            _HELP["kwok_lane_stage_seconds"],
            ("shard", "stage"),
        )
        self.stage_hists = {
            s: fam.labels(shard=self.lane_id, stage=s) for s in LANE_STAGES
        }
        self._depth = r.gauge(
            "kwok_lane_queue_depth",
            _HELP["kwok_lane_queue_depth"],
            ("shard",),
        ).labels(shard=self.lane_id)
        self._routed = r.counter(
            "kwok_route_partition_events_total",
            _HELP["kwok_route_partition_events_total"],
            ("shard",),
        ).labels(shard=self.lane_id)

    def observe_stage(self, stage: str, seconds: float) -> None:
        self.stage_hists[stage].observe(seconds)
        self.parent.observe_stage(stage, seconds)

    def inc_routed(self, n: int) -> None:
        self._routed.inc(n)

    def set_queue_depth(self, depth: int) -> None:
        self._depth.set(depth)

    @property
    def stage_sums(self) -> dict:
        """Per-lane stage second totals (lane-utilization reporting)."""
        return {s: h.sum for s, h in self.stage_hists.items()}


# --------------------------------------------------- proc-lane merge policy
# (ISSUE 16: each lane child is a whole single-lane engine whose registry
# snapshot crosses the process boundary via a MetricsBank slab. The
# parent folds every snapshot into ONE scratch registry per scrape so the
# proc-lane exposition is family-and-label identical to the threaded
# one: child stage histograms label-split into kwok_lane_stage_seconds
# {shard=} AND aggregate into the unlabeled stage family — exactly what
# LaneTelemetry.observe_stage does in-process — while counters and
# histograms sum and gauges follow the explicit policy below.)

# gauges where the fleet-wide value is the sum of the lanes' values
PROC_MERGE_SUM_GAUGES = frozenset({
    "kwok_tick_inflight",
    "kwok_checkpoint_rows",
})
# gauges where the fleet-wide value is the worst lane's value
PROC_MERGE_MAX_GAUGES = frozenset({
    "kwok_tick_seconds_last",
    "kwok_watch_lag_seconds_last",
    "kwok_restart_recovery_seconds",
})
# gauges the parent computes itself (StatusBank scrape / build identity):
# a lane's copy is dropped, never double-counted
PROC_MERGE_PARENT_GAUGES = frozenset({
    "kwok_build_info",
    "kwok_nodes_managed",
    "kwok_pods_managed",
    "kwok_ingest_queue_depth",
    "kwok_shm_arena_bytes",
})


def _merge_lane_snapshot(reg, shard: int, snap: dict,
                         include_gauges: bool) -> None:
    from kwok_tpu.telemetry.registry import family_from_doc, merge_child

    lane_fam = reg.histogram(
        "kwok_lane_stage_seconds", _HELP["kwok_lane_stage_seconds"],
        ("shard", "stage"),
    )
    for name, doc in sorted(snap.items()):
        t = doc.get("type")
        if name == "kwok_tick_stage_seconds":
            # aggregate into the whole-engine stage family AND label-split
            # drain/emit under the lane's shard — the LaneTelemetry shape
            fam = family_from_doc(reg, name, doc)
            for values, v in doc.get("children", ()):
                merge_child(fam, values, v)
                stage = str(values[-1]) if values else ""
                if stage in LANE_STAGES:
                    merge_child(lane_fam, (str(shard), stage), v)
            continue
        if name in PROC_MERGE_PARENT_GAUGES:
            continue
        if name == "kwok_ingest_queue_depth":
            continue  # label-split from the StatusBank, not the snapshot
        if t == "gauge":
            if not include_gauges:
                continue  # a retired lane's gauges are stale by definition
            if name in PROC_MERGE_SUM_GAUGES:
                mode = "sum"
            elif name in PROC_MERGE_MAX_GAUGES:
                mode = "max"
            else:
                continue  # unlisted gauges stay parent-authoritative
            fam = family_from_doc(reg, name, doc)
            for values, v in doc.get("children", ()):
                merge_child(fam, values, v, gauge=mode)
            continue
        fam = family_from_doc(reg, name, doc)
        for values, v in doc.get("children", ()):
            merge_child(fam, values, v)


def merge_proc_lane_metrics(parent_snap: dict, lane_snaps: dict,
                            retired_snaps: dict, n: int,
                            queue_depths: "dict | None" = None):
    """One scratch registry for a proc-lane scrape: the parent's own
    snapshot, every live lane's engine snapshot (``{shard: snap}``), and
    each lane's retired accumulator (previous incarnations' final
    snapshots — counters/histograms only, so aggregates stay monotonic
    across respawns). ``queue_depths`` feeds kwok_lane_queue_depth from
    the StatusBank (fresher than any 1s-cadence snapshot). Lane families
    are pre-created for every shard so the exposition is stable from the
    first scrape, before any child has published."""
    from kwok_tpu.telemetry.registry import registry_from_snapshot

    reg = registry_from_snapshot(parent_snap)
    lane_fam = reg.histogram(
        "kwok_lane_stage_seconds", _HELP["kwok_lane_stage_seconds"],
        ("shard", "stage"),
    )
    depth_fam = reg.gauge(
        "kwok_lane_queue_depth", _HELP["kwok_lane_queue_depth"], ("shard",)
    )
    for i in range(n):
        for s in LANE_STAGES:
            lane_fam.labels(shard=str(i), stage=s)
        depth_fam.labels(shard=str(i)).set(
            int((queue_depths or {}).get(i, 0))
        )
    for shard, snap in sorted(retired_snaps.items()):
        if snap:
            _merge_lane_snapshot(reg, shard, snap, include_gauges=False)
    for shard, snap in sorted(lane_snaps.items()):
        if snap:
            _merge_lane_snapshot(reg, shard, snap, include_gauges=True)
    return reg
