"""Overload-protection metric surface shared by both mock apiservers.

The Python mock (``edge/mockserver.py``) and the native C++ twin
(``native/apiserver.cc``) expose the same three families on their own
``/metrics`` endpoint so overload gates (``benchmarks/watcher_fleet.py``)
can scrape either server identically:

- ``kwok_apiserver_inflight{band=}`` — requests currently admitted per
  max-inflight band ("readonly" = LIST/GET, "mutating" =
  POST/PATCH/DELETE; watches are long-running and exempt, bounded by the
  per-watcher send buffer instead).
- ``kwok_apiserver_rejected_total{band=}`` — requests answered 429 +
  ``Retry-After`` because the band was saturated (kube-apiserver
  ``--max-requests-inflight`` / ``--max-mutating-requests-inflight``
  semantics: reject, never queue unboundedly).
- ``kwok_watch_terminations_total{reason=}`` — watch streams the server
  closed: ``slow`` = the consumer stopped reading and its bounded send
  buffer overflowed (the watch cache's slow-consumer termination; the
  client re-lists), ``deadline`` = the request's ``timeoutSeconds``
  expired (clean close at an event boundary; the client resumes from its
  last revision).

The counters themselves are plain ints owned by the store/server objects
(they are bumped under the store lock, where taking a registry child lock
would nest two level-85 leaves); this module renders them into the strict
Prometheus text format the rest of the tree uses.
"""

from __future__ import annotations

import bisect
import collections
import os
import threading
import time

BANDS = ("readonly", "mutating")
TERMINATION_REASONS = ("slow", "deadline")

# ---------------------------------------------------------- phase timing
#
# ISSUE 11: both mock apiservers measure where a request's wall time goes
# and expose the same histogram families so the latency-attribution gate
# (benchmarks/latency_attrib.py) can scrape either server identically.
# All clock reads are gated by KWOK_TPU_APISERVER_TIMING (default on;
# "0" disables every per-request stamp — the families still render, with
# zero counts, so scrapes stay shape-stable).
#
# The reconciliation contract: for every unary request,
#   read_headers + read_body + parse + commit + encode ~= request total
# within a small in-handler glue residue (band check, path match — a few
# hundred ns). `fanout` is the per-watcher encode+push SUBSET of commit
# (Store emit runs under the store lock) and is therefore excluded from
# the phase sum; `kwok_watch_fanout_total` counts watcher pushes so
# fanout_sum / fanout_total is the per-watcher encode+push cost.

#: whether per-request clock stamps are taken (module-level so the
#: Python mock reads it once, like the C++ twin's cached getenv)
TIMING_ENABLED = os.environ.get("KWOK_TPU_APISERVER_TIMING", "1") != "0"

#: phases every unary request is attributed to, in reconciliation order;
#: fanout last (the disclosed commit subset, excluded from the sum)
TIMING_PHASES = (
    "read_headers", "read_body", "parse", "commit", "encode", "fanout",
)

#: audit-verb vocabulary of the request-level total histogram (watch
#: streams are long-running and excluded from timing entirely)
TIMING_VERBS = ("get", "list", "create", "patch", "delete", "other")

#: fixed bucket ladder (seconds) shared by every timing family; the
#: label strings are canonical — apiserver.cc renders these exact bytes
TIMING_BUCKETS = (
    (5e-06, "5e-06"), (1e-05, "1e-05"), (2.5e-05, "2.5e-05"),
    (5e-05, "5e-05"), (0.0001, "0.0001"), (0.00025, "0.00025"),
    (0.0005, "0.0005"), (0.001, "0.001"), (0.0025, "0.0025"),
    (0.005, "0.005"), (0.01, "0.01"), (0.025, "0.025"), (0.05, "0.05"),
    (0.1, "0.1"), (0.25, "0.25"), (0.5, "0.5"), (1, "1"),
)
_BOUNDS = [b for b, _ in TIMING_BUCKETS]

#: flight-recorder ring capacity (recent request records kept for
#: /debug/flight post-mortems); mirrored by apiserver.cc
FLIGHT_CAPACITY = 1024

#: bucket ladder (events, power-of-2) for kwok_watch_cursor_lag_events;
#: canonical label strings — apiserver.cc renders these exact bytes
LAG_EVENT_BUCKETS = (
    (1, "1"), (2, "2"), (4, "4"), (8, "8"), (16, "16"), (32, "32"),
    (64, "64"), (128, "128"), (256, "256"), (512, "512"),
    (1024, "1024"), (2048, "2048"), (4096, "4096"),
)
_LAG_BOUNDS = [b for b, _ in LAG_EVENT_BUCKETS]


class LagHist:
    """Fixed-bucket histogram over EVENT COUNTS (integer sum), observed
    once per watch close with the stream's final ring-cursor lag — the
    census surface (ISSUE 16) the C10k reactor rewrite will be graded
    against. Plain ints bumped under the store's ring lock."""

    __slots__ = ("counts", "sum_events", "count")

    def __init__(self) -> None:
        self.counts = [0] * (len(_LAG_BOUNDS) + 1)
        self.sum_events = 0
        self.count = 0

    def observe(self, events: int) -> None:
        self.counts[bisect.bisect_left(_LAG_BOUNDS, events)] += 1
        self.sum_events += int(events)
        self.count += 1


class PhaseHist:
    """One fixed-bucket histogram: a counts array, a float sum and a
    total count, bumped under the GIL (the C++ twin uses atomics). The
    render is cumulative-bucket Prometheus text."""

    __slots__ = ("counts", "sum_s", "count")

    def __init__(self) -> None:
        self.counts = [0] * (len(_BOUNDS) + 1)
        self.sum_s = 0.0
        self.count = 0

    def observe(self, seconds: float) -> None:
        # bisect_left: `le` is inclusive, so a value equal to a boundary
        # lands in that boundary's bucket (matches the registry histogram
        # and the C++ twin's <= compare)
        self.counts[bisect.bisect_left(_BOUNDS, seconds)] += 1
        self.sum_s += seconds
        self.count += 1


class ApiserverTiming:
    """Per-server phase timing + flight recorder (the Python half of the
    parity-pinned surface; apiserver.cc is the native twin).

    All counters are plain ints/floats written under the GIL from the
    handler thread that served the request; the flight ring is a bounded
    deque (thread-safe appends). ``tls`` carries the per-request fanout
    accumulator from the store's emit loop back to the handler that
    triggered it (same thread)."""

    def __init__(self, enabled: "bool | None" = None) -> None:
        self.enabled = TIMING_ENABLED if enabled is None else bool(enabled)
        self.phases = {p: PhaseHist() for p in TIMING_PHASES}
        self.verbs = {v: PhaseHist() for v in TIMING_VERBS}
        self.flight: "collections.deque" = collections.deque(
            maxlen=FLIGHT_CAPACITY
        )
        self.captured = 0
        #: high-watermark of retained ring-cursor lag (ISSUE 13: the
        #: bounded-buffer proof folded into the broadcast ring) — always
        #: tracked, because the fleet gate's bounded-buffer proof must
        #: not depend on the timing env knob
        self.backlog_peak = 0
        self.fanout_pushes = 0
        self.tls = threading.local()

    def begin_request(self) -> "float | None":
        """Arm the per-request fanout accumulator; returns the request
        t0 (perf_counter) or None when timing is off."""
        if not self.enabled:
            return None
        self.tls.fanout_s = 0.0
        return time.perf_counter()

    def note_fanout(self, seconds: float) -> None:
        """Called by the store's commit section after the one ring
        encode+append (same thread as the handler that triggered the
        write); the push COUNT is accounted separately at emit
        (events x live watchers of the kind)."""
        if getattr(self.tls, "fanout_s", None) is not None:
            self.tls.fanout_s += seconds

    def observe_request(
        self, verb: str, total_s: float, phase_s: dict
    ) -> None:
        """One unary request completed: observe the verb total and every
        phase that occurred (parse only on body verbs, fanout only when a
        watcher was pushed — mirrored by apiserver.cc)."""
        self.verbs.get(verb, self.verbs["other"]).observe(total_s)
        for p, v in phase_s.items():
            self.phases[p].observe(v)

    def record_flight(
        self, method: str, path: str, status: int, band: str,
        ts_unix: float, total_us: float, phases_us: dict,
    ) -> None:
        self.captured += 1
        self.flight.append({
            "method": method,
            "path": path,
            "status": int(status),
            "band": band,
            "ts_unix": round(ts_unix, 6),
            "total_us": round(total_us, 3),
            "phases_us": {
                p: round(float(phases_us.get(p, 0.0)), 3)
                for p in TIMING_PHASES
            },
        })

    def flight_doc(self, server: str) -> dict:
        """The /debug/flight document (schema shared with apiserver.cc;
        validated by kwok_tpu.telemetry.timeline.check_flight)."""
        return {
            "server": server,
            "timing_enabled": bool(self.enabled),
            "ring_capacity": FLIGHT_CAPACITY,
            "captured": self.captured,
            "records": list(self.flight),
        }


APISERVER_METRICS_HELP = {
    "kwok_apiserver_inflight": (
        "Requests currently admitted per max-inflight band "
        "(readonly=LIST/GET, mutating=POST/PATCH/DELETE; watches exempt)"
    ),
    "kwok_apiserver_rejected_total": (
        "Requests rejected with 429 + Retry-After because the band's "
        "max-inflight limit was saturated"
    ),
    "kwok_watch_terminations_total": (
        "Watch streams closed by the server (slow=send-buffer overflow "
        "from a consumer that stopped reading, deadline=timeoutSeconds "
        "expiry)"
    ),
    "kwok_apiserver_request_phase_seconds": (
        "Per-request phase seconds inside the mock apiserver "
        "(read_headers+read_body+parse+commit+encode reconcile to the "
        "request total; fanout is the serialize-once ring encode+append "
        "subset of commit and is excluded from the sum)"
    ),
    "kwok_apiserver_request_seconds": (
        "End-to-end seconds per unary request by audit verb (first "
        "request bytes to response queued; watch streams are long-"
        "running and excluded)"
    ),
    "kwok_watch_fanout_total": (
        "Watch events delivered to individual watchers via the "
        "broadcast ring (events x live watchers of the kind at emit; "
        "fanout_sum over this count is the AMORTIZED per-watcher encode "
        "cost — the ring encodes once and shares the bytes)"
    ),
    "kwok_apiserver_watchers": (
        "Live watch streams currently registered"
    ),
    "kwok_watch_backlog_events": (
        "Per-watcher ring-cursor lag across live watches (agg=max/"
        "total) and the high-watermark of retained lag (agg=peak; "
        "never exceeds KWOK_TPU_WATCH_BACKLOG while the slow-consumer "
        "cap enforces — the bounded-buffer proof, now measured as ring "
        "lag)"
    ),
    "kwok_watch_ring_lag": (
        "Ring-cursor lag behind the serialize-once broadcast ring head "
        "per live watch stream (agg=max/total) and its all-time "
        "high-watermark (agg=peak, clamped to the backlog cap on a "
        "slow-close; identical to kwok_watch_backlog_events by "
        "construction — the explicit ring-surface name)"
    ),
    "kwok_watch_encode_total": (
        "Watch events encoded into the broadcast ring — exactly ONE "
        "encode per event no matter the watcher count (the "
        "serialize-once proof; kwok_watch_fanout_total counts the "
        "deliveries the shared bytes fan out to)"
    ),
    "kwok_watch_cursor_lag_events": (
        "Final ring-cursor lag (events behind the broadcast ring head) "
        "observed once per watch close: slow terminations record the "
        "overflow that killed the stream, graceful closes the drained "
        "tail; per-watcher live lag is GET /debug/watchers"
    ),
}


def render_apiserver_metrics(
    inflight: dict, rejected: dict, terminations: dict
) -> bytes:
    """Strict Prometheus exposition of the three families. All three dicts
    are read without locks: values are ints written under the GIL."""
    lines: list[str] = []

    def fam(name: str, type_: str, samples: list) -> None:
        lines.append(f"# HELP {name} {APISERVER_METRICS_HELP[name]}")
        lines.append(f"# TYPE {name} {type_}")
        lines.extend(samples)

    fam(
        "kwok_apiserver_inflight", "gauge",
        [
            f'kwok_apiserver_inflight{{band="{b}"}} {int(inflight.get(b, 0))}'
            for b in BANDS
        ],
    )
    fam(
        "kwok_apiserver_rejected_total", "counter",
        [
            f'kwok_apiserver_rejected_total{{band="{b}"}} '
            f"{int(rejected.get(b, 0))}"
            for b in BANDS
        ],
    )
    fam(
        "kwok_watch_terminations_total", "counter",
        [
            f'kwok_watch_terminations_total{{reason="{r}"}} '
            f"{int(terminations.get(r, 0))}"
            for r in TERMINATION_REASONS
        ],
    )
    return ("\n".join(lines) + "\n").encode()


def _hist_lines(
    name: str, label: str, value: str, h: PhaseHist
) -> "list[str]":
    """Cumulative-bucket text for one labeled child; the exact line shapes
    apiserver.cc mirrors byte-for-byte."""
    out = []
    acc = 0
    for i, (_b, le) in enumerate(TIMING_BUCKETS):
        acc += h.counts[i]
        out.append(
            f'{name}_bucket{{{label}="{value}",le="{le}"}} {acc}'
        )
    # count is read last; clamp so a concurrent observe mid-render can
    # never leave the +Inf bucket below a finite one (C++ twin does the
    # same)
    cnt = max(h.count, acc + h.counts[-1])
    out.append(
        f'{name}_bucket{{{label}="{value}",le="+Inf"}} {cnt}'
    )
    out.append(f'{name}_sum{{{label}="{value}"}} {h.sum_s:.9f}')
    out.append(f'{name}_count{{{label}="{value}"}} {cnt}')
    return out


def _lag_hist_lines(h: "LagHist | None") -> "list[str]":
    """Cumulative-bucket text for the (label-less) watch-close lag
    histogram; the exact line shapes apiserver.cc mirrors."""
    h = h or LagHist()
    name = "kwok_watch_cursor_lag_events"
    out = []
    acc = 0
    for i, (_b, le) in enumerate(LAG_EVENT_BUCKETS):
        acc += h.counts[i]
        out.append(f'{name}_bucket{{le="{le}"}} {acc}')
    cnt = max(h.count, acc + h.counts[-1])
    out.append(f'{name}_bucket{{le="+Inf"}} {cnt}')
    out.append(f"{name}_sum {int(h.sum_events)}")
    out.append(f"{name}_count {cnt}")
    return out


def render_timing_metrics(
    timing: ApiserverTiming, backlogs, encode_total: int = 0,
    lag_hist: "LagHist | None" = None,
) -> bytes:
    """The phase-timing families, appended to the overload surface by both
    servers' /metrics handlers. Always renders the FULL phase/verb matrix
    (zero counts when nothing was observed, or when timing is disabled)
    so scrapes — and the byte-compared parity twins — are shape-stable.
    ``backlogs`` is the live per-watcher ring-cursor lags;
    ``encode_total`` the store's one-encode-per-event ring counter."""
    lines: list[str] = []

    def fam(name: str, type_: str, samples: list) -> None:
        lines.append(f"# HELP {name} {APISERVER_METRICS_HELP[name]}")
        lines.append(f"# TYPE {name} {type_}")
        lines.extend(samples)

    phase_samples: list[str] = []
    for p in TIMING_PHASES:
        phase_samples.extend(
            _hist_lines(
                "kwok_apiserver_request_phase_seconds", "phase", p,
                timing.phases[p],
            )
        )
    fam("kwok_apiserver_request_phase_seconds", "histogram", phase_samples)
    verb_samples: list[str] = []
    for v in TIMING_VERBS:
        verb_samples.extend(
            _hist_lines(
                "kwok_apiserver_request_seconds", "verb", v,
                timing.verbs[v],
            )
        )
    fam("kwok_apiserver_request_seconds", "histogram", verb_samples)
    fam(
        "kwok_watch_fanout_total", "counter",
        [f"kwok_watch_fanout_total {int(timing.fanout_pushes)}"],
    )
    backlogs = list(backlogs)
    fam(
        "kwok_apiserver_watchers", "gauge",
        [f"kwok_apiserver_watchers {len(backlogs)}"],
    )
    lag_samples = [
        str(max(backlogs) if backlogs else 0),
        str(sum(backlogs)),
        str(int(timing.backlog_peak)),
    ]
    fam(
        "kwok_watch_backlog_events", "gauge",
        [
            f'kwok_watch_backlog_events{{agg="{agg}"}} {v}'
            for agg, v in zip(("max", "total", "peak"), lag_samples)
        ],
    )
    fam(
        "kwok_watch_ring_lag", "gauge",
        [
            f'kwok_watch_ring_lag{{agg="{agg}"}} {v}'
            for agg, v in zip(("max", "total", "peak"), lag_samples)
        ],
    )
    fam(
        "kwok_watch_encode_total", "counter",
        [f"kwok_watch_encode_total {int(encode_total)}"],
    )
    fam(
        "kwok_watch_cursor_lag_events", "histogram",
        _lag_hist_lines(lag_hist),
    )
    return ("\n".join(lines) + "\n").encode()
