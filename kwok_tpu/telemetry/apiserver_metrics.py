"""Overload-protection metric surface shared by both mock apiservers.

The Python mock (``edge/mockserver.py``) and the native C++ twin
(``native/apiserver.cc``) expose the same three families on their own
``/metrics`` endpoint so overload gates (``benchmarks/watcher_fleet.py``)
can scrape either server identically:

- ``kwok_apiserver_inflight{band=}`` — requests currently admitted per
  max-inflight band ("readonly" = LIST/GET, "mutating" =
  POST/PATCH/DELETE; watches are long-running and exempt, bounded by the
  per-watcher send buffer instead).
- ``kwok_apiserver_rejected_total{band=}`` — requests answered 429 +
  ``Retry-After`` because the band was saturated (kube-apiserver
  ``--max-requests-inflight`` / ``--max-mutating-requests-inflight``
  semantics: reject, never queue unboundedly).
- ``kwok_watch_terminations_total{reason=}`` — watch streams the server
  closed: ``slow`` = the consumer stopped reading and its bounded send
  buffer overflowed (the watch cache's slow-consumer termination; the
  client re-lists), ``deadline`` = the request's ``timeoutSeconds``
  expired (clean close at an event boundary; the client resumes from its
  last revision).

The counters themselves are plain ints owned by the store/server objects
(they are bumped under the store lock, where taking a registry child lock
would nest two level-85 leaves); this module renders them into the strict
Prometheus text format the rest of the tree uses.
"""

from __future__ import annotations

BANDS = ("readonly", "mutating")
TERMINATION_REASONS = ("slow", "deadline")

APISERVER_METRICS_HELP = {
    "kwok_apiserver_inflight": (
        "Requests currently admitted per max-inflight band "
        "(readonly=LIST/GET, mutating=POST/PATCH/DELETE; watches exempt)"
    ),
    "kwok_apiserver_rejected_total": (
        "Requests rejected with 429 + Retry-After because the band's "
        "max-inflight limit was saturated"
    ),
    "kwok_watch_terminations_total": (
        "Watch streams closed by the server (slow=send-buffer overflow "
        "from a consumer that stopped reading, deadline=timeoutSeconds "
        "expiry)"
    ),
}


def render_apiserver_metrics(
    inflight: dict, rejected: dict, terminations: dict
) -> bytes:
    """Strict Prometheus exposition of the three families. All three dicts
    are read without locks: values are ints written under the GIL."""
    lines: list[str] = []

    def fam(name: str, type_: str, samples: list) -> None:
        lines.append(f"# HELP {name} {APISERVER_METRICS_HELP[name]}")
        lines.append(f"# TYPE {name} {type_}")
        lines.extend(samples)

    fam(
        "kwok_apiserver_inflight", "gauge",
        [
            f'kwok_apiserver_inflight{{band="{b}"}} {int(inflight.get(b, 0))}'
            for b in BANDS
        ],
    )
    fam(
        "kwok_apiserver_rejected_total", "counter",
        [
            f'kwok_apiserver_rejected_total{{band="{b}"}} '
            f"{int(rejected.get(b, 0))}"
            for b in BANDS
        ],
    )
    fam(
        "kwok_watch_terminations_total", "counter",
        [
            f'kwok_watch_terminations_total{{reason="{r}"}} '
            f"{int(terminations.get(r, 0))}"
            for r in TERMINATION_REASONS
        ],
    )
    return ("\n".join(lines) + "\n").encode()
