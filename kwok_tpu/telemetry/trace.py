"""Always-on, low-overhead span tracer: a bounded ring of stage spans.

The cost model attributes per-pod cost from aggregate counters (tick sum,
drain sum, pump sum) and still leaves a residual it cannot see — the gaps
*between* stages: a wire that landed but waited a drain window to be
consumed, a patch batch that sat in the executor queue. Spans make those
gaps visible: each is (name, start, duration, lane, args) recorded into a
preallocated ring — one index increment + one slot store, no allocation
beyond the record tuple, no lock (a concurrent append may overwrite one
slot; losing one span under contention is the accepted price of staying
off the hot path).

Export is Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
format): complete events (``"ph": "X"``) with microsecond timestamps
relative to the tracer's epoch, one ``tid`` lane per engine stage family
so dispatch / consume / emit / pump stack visually per tick.
"""

from __future__ import annotations

import json
import time

# Stable lane ids: spans from different engine threads land in named lanes
# instead of raw thread idents, so two runs diff cleanly.
LANES = {
    "drain": 1,
    "dispatch": 2,
    "consume": 3,
    "emit": 4,
    "pump": 5,
    "patch": 6,
    "event": 7,
}


class Tracer:
    """Bounded span ring. ``capacity`` spans are kept; older spans are
    overwritten (the tail of a run is what post-mortems need)."""

    def __init__(self, capacity: int = 65536, enabled: bool = True):
        self.capacity = int(capacity)
        self.enabled = enabled
        self._buf: list = [None] * self.capacity
        self._i = 0
        self.recorded = 0  # total spans ever recorded (ring may have fewer)
        # epoch: perf_counter anchor for span timestamps + the wall clock
        # it corresponds to (exported so dumps from one run line up)
        self.epoch_perf = time.perf_counter()
        self.epoch_unix = time.time()

    def span(self, name: str, t0: float, t1: float, lane: str = "drain",
             args=None) -> None:
        """Record a completed span; t0/t1 are time.perf_counter() values."""
        if not self.enabled:
            return
        i = self._i
        self._i = (i + 1) % self.capacity
        self._buf[i] = (name, t0, t1, lane, args)
        self.recorded += 1

    # ------------------------------------------------------------- export

    def events(self) -> list:
        """Spans in ring order as Chrome trace-event dicts."""
        i = self._i
        ordered = self._buf[i:] + self._buf[:i]
        ep = self.epoch_perf
        out = []
        seen_lanes = set()
        for rec in ordered:
            if rec is None:
                continue
            name, t0, t1, lane, args = rec
            tid = LANES.get(lane, 0)
            seen_lanes.add((lane, tid))
            ev = {
                "name": name,
                "ph": "X",
                "ts": round((t0 - ep) * 1e6, 1),
                "dur": round(max(0.0, t1 - t0) * 1e6, 1),
                "pid": 0,
                "tid": tid,
                "cat": "kwok",
            }
            if args:
                ev["args"] = args
            out.append(ev)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 0,
                "tid": tid,
                "args": {"name": lane},
            }
            for lane, tid in sorted(seen_lanes)
        ]
        return meta + out

    def chrome_trace(self, extra_events=None) -> dict:
        events = self.events()
        if extra_events:
            events = events + list(extra_events)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix": self.epoch_unix,
                "spans_recorded": self.recorded,
                "ring_capacity": self.capacity,
            },
        }

    def dump(self, path: str, extra_events=None) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(extra_events), f)


def merge_chrome_traces(tracers, labels=None) -> dict:
    """One Chrome trace document from several tracers (federation: the fed
    loop's tracer + each member's). Per-tracer events land under their own
    ``pid`` with a process_name metadata record, and timestamps are
    re-anchored to the EARLIEST tracer epoch so lanes line up."""
    tracers = list(tracers)
    if not tracers:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(t.epoch_perf for t in tracers)
    events = []
    for pid, t in enumerate(tracers):
        shift = round((t.epoch_perf - base) * 1e6, 1)
        label = (
            labels[pid] if labels and pid < len(labels) else f"tracer{pid}"
        )
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": label},
        })
        for ev in t.events():
            ev = dict(ev)
            ev["pid"] = pid
            if ev["ph"] != "M":
                ev["ts"] = round(ev["ts"] + shift, 1)
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"epoch_unix": min(t.epoch_unix for t in tracers)},
    }
