"""kwok_tpu.telemetry: metrics registry + span tracing for the engine.

Three pieces (ISSUE 1 tentpole):

- ``registry``: a lock-light Prometheus-style registry — counters, gauges,
  fixed-bucket histograms with label support — rendering the text
  exposition format with real ``_bucket``/``_sum``/``_count`` series.
- ``trace``: a bounded ring-buffer span tracer exporting Chrome
  trace-event JSON (``/debug/trace``), attributing per-tick wall time to
  named stages (dispatch → consume → emit → pump ack).
- ``engine_metrics``: the engine's named handles over both, plus the
  legacy flat-dict view older tooling still reads.
"""

from kwok_tpu.telemetry.engine_metrics import (
    EngineTelemetry,
    LaneTelemetry,
    register_build_info,
)
from kwok_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    CounterFamily,
    GaugeFamily,
    HistogramFamily,
    MetricsRegistry,
)
from kwok_tpu.telemetry.timeline import check_flight, merge_timeline
from kwok_tpu.telemetry.trace import Tracer, merge_chrome_traces

__all__ = [
    "DEFAULT_LATENCY_BUCKETS",
    "CounterFamily",
    "EngineTelemetry",
    "GaugeFamily",
    "HistogramFamily",
    "LaneTelemetry",
    "MetricsRegistry",
    "Tracer",
    "check_flight",
    "merge_chrome_traces",
    "merge_timeline",
    "register_build_info",
]
