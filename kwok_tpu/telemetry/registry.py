"""Lock-light metrics registry: counters, gauges, fixed-bucket histograms.

Replaces the engine's ad-hoc ``metrics`` dict + one global ``_metrics_lock``
(every increment from every thread used to serialize on it). Here each
*child* (one label combination of one family) owns its own tiny lock held
for a single read-modify-write — uncontended in the common case because hot
metrics are written by exactly one thread (the tick thread) — and a
histogram observe is one bisect + one array increment. Rendering walks the
families and emits the Prometheus text exposition format 0.0.4: ``# HELP``
/ ``# TYPE`` once per family, label escaping per the spec, and real
histogram series (``_bucket`` with cumulative ``le`` counts incl. ``+Inf``,
``_sum``, ``_count``) instead of the bare ``*_seconds_sum`` counters the
old surface exported with no matching ``_count``.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

# Latency buckets (seconds): 100us .. 10s, the range a tick/drain/patch can
# plausibly land in; fixed at registration so observe stays index+increment.
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(text: str) -> str:
    return (
        text.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def format_value(v) -> str:
    """Prometheus float formatting: integral values print without the
    trailing .0 (matches what real client libraries emit)."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _labels_suffix(label_names, label_values, extra: str = "") -> str:
    parts = [
        f'{n}="{escape_label_value(str(v))}"'
        for n, v in zip(label_names, label_values)
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, v=1) -> None:
        with self._lock:
            self.value += v


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v) -> None:
        self.value = v  # single STORE: atomic under the GIL

    def inc(self, v=1) -> None:
        with self._lock:
            self.value += v


class _HistogramChild:
    __slots__ = ("_lock", "bounds", "counts", "sum")

    def __init__(self, bounds):
        self._lock = threading.Lock()
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf
        self.sum = 0.0

    def observe(self, v: float) -> None:
        i = bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v

    @property
    def count(self) -> int:
        return sum(self.counts)


class _Family:
    """One metric family: a name, a type, and children per label combo."""

    _child_cls: type

    def __init__(self, name: str, help: str, label_names=()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        if not self.label_names:
            # label-less family: the bare child exists from birth so the
            # family always renders (a declared TYPE with no sample is a
            # strict-parser error in our own oracle)
            self._children[()] = self._make_child()

    def _make_child(self):
        return self._child_cls()

    def labels(self, **kw):
        values = tuple(str(kw[n]) for n in self.label_names)
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._make_child())
        return child

    @property
    def child(self):
        """The label-less child (only valid when label_names is empty)."""
        return self._children[()]

    def children(self):
        # snapshot under the family lock: labels() may be inserting a
        # first-seen child (e.g. a new patch path) from another thread
        # while a scrape renders this family
        with self._lock:
            return sorted(self._children.items())


class CounterFamily(_Family):
    type = "counter"
    _child_cls = _CounterChild

    def inc(self, v=1) -> None:
        self.child.inc(v)

    def render(self, out: list) -> None:
        for values, c in self.children():
            out.append(
                f"{self.name}{_labels_suffix(self.label_names, values)}"
                f" {format_value(c.value)}"
            )


class GaugeFamily(_Family):
    type = "gauge"
    _child_cls = _GaugeChild

    def set(self, v) -> None:
        self.child.set(v)

    @property
    def value(self):
        return self.child.value

    def render(self, out: list) -> None:
        for values, c in self.children():
            out.append(
                f"{self.name}{_labels_suffix(self.label_names, values)}"
                f" {format_value(c.value)}"
            )


class HistogramFamily(_Family):
    type = "histogram"

    def __init__(self, name, help, label_names=(), buckets=None):
        self.buckets = tuple(buckets or DEFAULT_LATENCY_BUCKETS)
        super().__init__(name, help, label_names)

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self.child.observe(v)

    def render(self, out: list) -> None:
        for values, c in self.children():
            # snapshot once: concurrent observes between bucket lines would
            # otherwise break cumulative monotonicity in the scrape
            with c._lock:
                counts = list(c.counts)
                total = sum(counts)
                s = c.sum
            acc = 0
            for bound, n in zip(c.bounds, counts):
                acc += n
                extra = 'le="%s"' % format_value(float(bound))
                out.append(
                    f"{self.name}_bucket"
                    f"{_labels_suffix(self.label_names, values, extra)}"
                    f" {acc}"
                )
            inf = _labels_suffix(self.label_names, values, 'le="+Inf"')
            out.append(f"{self.name}_bucket{inf} {total}")
            suffix = _labels_suffix(self.label_names, values)
            out.append(f"{self.name}_sum{suffix} {format_value(s)}")
            out.append(f"{self.name}_count{suffix} {total}")


class MetricsRegistry:
    """Family registrar + text-exposition renderer. ``counter`` / ``gauge``
    / ``histogram`` are get-or-create: federation members registering the
    same family share it (their per-shard children coexist as labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, cls, name, help, label_names, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, label_names, **kw)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise ValueError(
                    f"metric {name} already registered as {fam.type}"
                )
            elif tuple(label_names) != fam.label_names:
                raise ValueError(
                    f"metric {name} label mismatch: "
                    f"{fam.label_names} vs {tuple(label_names)}"
                )
            return fam

    def counter(self, name, help="", label_names=()) -> CounterFamily:
        return self._get_or_create(CounterFamily, name, help, label_names)

    def gauge(self, name, help="", label_names=()) -> GaugeFamily:
        return self._get_or_create(GaugeFamily, name, help, label_names)

    def histogram(
        self, name, help="", label_names=(), buckets=None
    ) -> HistogramFamily:
        return self._get_or_create(
            HistogramFamily, name, help, label_names, buckets=buckets
        )

    def render(self) -> str:
        out: list[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for fam in families:
            if not fam._children:
                continue  # labeled family with no children yet: no series
            if fam.help:
                out.append(f"# HELP {fam.name} {escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.type}")
            fam.render(out)
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able dump of every family and child — the cross-process
        MetricsBank payload (ISSUE 16). Per family: type, help, label
        names, buckets (histograms), and ``children`` as
        ``[label_values, value]`` pairs where a histogram's value is
        ``[counts, sum]``. ``registry_from_snapshot`` round-trips it."""
        fams: dict = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            doc: dict = {
                "type": fam.type,
                "help": fam.help,
                "labels": list(fam.label_names),
                "children": [],
            }
            if fam.type == "histogram":
                doc["buckets"] = list(fam.buckets)
            for values, c in fam.children():
                if fam.type == "histogram":
                    with c._lock:
                        v = [list(c.counts), c.sum]
                else:
                    v = c.value
                doc["children"].append([list(values), v])
            fams[fam.name] = doc
        return fams


# --------------------------------------------------- snapshot merge plumbing
# (ISSUE 16: the parent's /metrics folds each lane child's registry
# snapshot into ONE scratch registry before rendering — the strict
# exposition oracle rejects duplicate TYPE declarations, so per-child
# text concatenation was never an option.)


def family_from_doc(registry: MetricsRegistry, name: str, doc: dict):
    """Get-or-create the family a snapshot doc describes."""
    t = doc.get("type")
    labels = tuple(doc.get("labels") or ())
    help_ = doc.get("help", "")
    if t == "counter":
        return registry.counter(name, help_, labels)
    if t == "gauge":
        return registry.gauge(name, help_, labels)
    if t == "histogram":
        return registry.histogram(
            name, help_, labels, buckets=doc.get("buckets")
        )
    raise ValueError(f"snapshot family {name}: unknown type {t!r}")


def merge_child(fam, label_values, value, gauge: str = "sum") -> None:
    """Fold one snapshot child's value into ``fam``'s child at
    ``label_values``: counters and histograms accumulate; gauges follow
    ``gauge`` ("sum" | "max" | "set")."""
    values = tuple(str(v) for v in label_values)
    child = fam.labels(**dict(zip(fam.label_names, values)))
    if fam.type == "histogram":
        counts, s = value
        if len(counts) != len(child.counts):
            return  # bucket-shape drift across versions: drop, never lie
        with child._lock:
            child.counts = [a + b for a, b in zip(child.counts, counts)]
            child.sum += s
    elif fam.type == "gauge":
        if gauge == "set":
            child.set(value)
        elif gauge == "max":
            child.set(max(child.value, value))
        else:
            child.inc(value)
    else:
        child.inc(value)


def registry_from_snapshot(snap: dict) -> MetricsRegistry:
    """Reconstruct a scratch registry (values included) from a
    ``MetricsRegistry.snapshot()`` document."""
    reg = MetricsRegistry()
    for name, doc in snap.items():
        fam = family_from_doc(reg, name, doc)
        for values, v in doc.get("children", ()):
            merge_child(fam, values, v, gauge="set")
    return reg


def fold_snapshot(acc: "dict | None", snap: dict) -> dict:
    """Accumulate one snapshot doc into ``acc`` at the dict level:
    counters and histogram counts/sums add, gauges take the newer value.
    This is the retired-lane accumulator — a respawned lane's counters
    restart at zero, so its predecessor's final snapshot must keep
    contributing or the parent's aggregated counters would decrease."""
    import json as _json

    snap = _json.loads(_json.dumps(snap))  # defensive deep copy
    if acc is None:
        return snap
    for name, doc in snap.items():
        adoc = acc.get(name)
        if adoc is None or adoc.get("type") != doc.get("type"):
            acc[name] = doc
            continue
        amap = {tuple(map(str, v)): val for v, val in adoc["children"]}
        for values, v in doc["children"]:
            key = tuple(map(str, values))
            old = amap.get(key)
            if old is None:
                adoc["children"].append([list(values), v])
                continue
            for pair in adoc["children"]:
                if tuple(map(str, pair[0])) != key:
                    continue
                if doc["type"] == "histogram":
                    counts = [a + b for a, b in zip(old[0], v[0])]
                    pair[1] = [counts, old[1] + v[1]]
                elif doc["type"] == "counter":
                    pair[1] = old + v
                else:
                    pair[1] = v
                break
    return acc
