"""Process-wide error accounting: swallowed exceptions + worker crashes.

The exception-hygiene rule (kwoklint ``silent-except``) bans broad
handlers that just ``pass``: a swallow must either log or bump
``kwok_swallowed_errors_total{site=...}`` here. The sites live in modules
with no engine handle (HTTP-client teardown, watch-stream cleanup, the
mock server's audit ring), so the counters ride a process-global registry
that the HTTP server appends to every ``/metrics`` render — the same way
it appends the process CPU collector.

Reading the series: most sites only move during shutdown (connection
teardown racing reader threads). A series climbing during steady state is
a bug report with the site name attached.
"""

from __future__ import annotations

import logging

from kwok_tpu.telemetry.registry import MetricsRegistry

logger = logging.getLogger("kwok_tpu.errors")

PROCESS_REGISTRY = MetricsRegistry()

_swallowed = PROCESS_REGISTRY.counter(
    "kwok_swallowed_errors_total",
    "Deliberately swallowed exceptions by site (shutdown races, "
    "best-effort cleanup); climbing outside shutdown means a bug",
    ("site",),
)
_crashes = PROCESS_REGISTRY.counter(
    "kwok_worker_crashes_total",
    "Uncaught exceptions that killed a spawned worker thread",
    ("thread",),
)
_restarts = PROCESS_REGISTRY.counter(
    "kwok_worker_restarts_total",
    "Crashed workers restarted by the resilience watchdog (within its "
    "restart budget); a crash WITHOUT a matching restart means the "
    "budget ran out and the engine went degraded",
    ("thread",),
)
_wire_rejects = PROCESS_REGISTRY.counter(
    "kwok_wire_rejects_total",
    "Corrupt or regressed wire input quarantined instead of applied: "
    "unparseable watch lines (reason=unparseable -> integrity resync), "
    "undecodable HTTP response bodies (http_body), watch-stream lines "
    "the client rejected mid-iteration (watch_line), and MODIFIED "
    "events whose resourceVersion regressed below the row's last "
    "ingested revision (stale_rv — routine after reconnect replays, "
    "hostile under wire.dup/wire.stale)",
    ("reason",),
)


def swallowed(site: str) -> None:
    """Record a deliberately swallowed exception. Call from inside an
    ``except`` block: the active exception lands in the debug log with a
    traceback, and the site's counter moves so /metrics shows it."""
    _swallowed.labels(site=site).inc()
    logger.debug("swallowed error at %s", site, exc_info=True)


def swallowed_total(site: str) -> int:
    """Test/diagnostic read of one site's counter."""
    return _swallowed.labels(site=site).value


def worker_crashed(thread_name: str) -> None:
    """Account an uncaught exception escaping a spawn_worker thread."""
    _crashes.labels(thread=thread_name).inc()


def worker_restarted(thread_name: str) -> None:
    """Account a watchdog restart of a crashed worker thread."""
    _restarts.labels(thread=thread_name).inc()


def worker_restarts_total(thread_name: str) -> int:
    """Test/diagnostic read of one thread's restart counter."""
    return _restarts.labels(thread=thread_name).value


def worker_crashes_total(thread_name: str) -> int:
    """Test/diagnostic read of one thread's crash counter."""
    return _crashes.labels(thread=thread_name).value


def worker_crash_ledger() -> dict:
    """Every thread's (crashes, restarts) pair — the 'zero unsupervised
    crashes' gate reads this: a crash without a matching restart means a
    worker died for good outside the watchdog's care."""
    out: dict = {}
    for (thread,), c in _crashes.children():
        out[thread] = [c.value, 0]
    for (thread,), c in _restarts.children():
        out.setdefault(thread, [0, 0])[1] = c.value
    return {k: tuple(v) for k, v in out.items()}


def wire_reject(reason: str, n: int = 1) -> None:
    """Account one quarantined corrupt/regressed wire record."""
    _wire_rejects.labels(reason=reason).inc(n)


def wire_rejects_total(reason: "str | None" = None) -> int:
    """Test/diagnostic read: one reason's tally, or the sum of all."""
    if reason is not None:
        return _wire_rejects.labels(reason=reason).value
    return sum(c.value for _values, c in _wire_rejects.children())


def render_nonempty() -> str:
    """Exposition text of the process registry, or "" when no counter has
    moved yet (labeled families with no children render no series)."""
    text = PROCESS_REGISTRY.render()
    return "" if not text.strip() else text
