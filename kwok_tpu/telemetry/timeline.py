"""Cross-tier latency timeline: stitch the engine's span ring and the
apiserver's flight recorder into ONE Chrome-trace document.

The engine's tracer (`telemetry/trace.py`) sees its own side of every
request — drain, emit, `pump.send`, sampled `pod.ingest_to_patch` spans
stamped with their (key, rv) correlation context — but the apiserver tier
was a black box until ISSUE 11: both mock apiservers now keep a bounded
flight ring of recent request records (method, path, per-phase µs, band,
status, wall stamp), dumped via ``GET /debug/flight``.

This module merges the two:

- ``merge_timeline(engine_trace, flight)`` re-anchors the flight records
  onto the engine trace's wall epoch (``otherData.epoch_unix``) and lands
  them in their own ``pid`` with per-phase ``tid`` lanes, so Perfetto
  shows a pump batch on the engine side overlapping the exact apiserver
  requests it carried.
- ``attribution(flight)`` / ``attribution_from_metrics(text)`` reduce a
  flight dump or a ``/metrics`` scrape to a per-phase µs table with the
  reconciliation the latency gate (`benchmarks/latency_attrib.py`)
  enforces: read_headers+read_body+parse+commit+encode vs the
  request-level total.

CLI::

    python -m kwok_tpu.telemetry.timeline \
        --trace /tmp/kwok-trace.json --flight /tmp/flight.json \
        --out /tmp/merged.json --table
"""

from __future__ import annotations

import json
import re

from kwok_tpu.telemetry.apiserver_metrics import TIMING_PHASES

#: phases whose per-request durations must reconcile to the request
#: total (fanout is the disclosed commit subset, excluded from the sum)
SUM_PHASES = ("read_headers", "read_body", "parse", "commit", "encode")

#: tid lanes for flight events in the merged document (0 = the request
#: span itself, then one lane per phase in vocabulary order)
_FLIGHT_LANES = {p: i + 1 for i, p in enumerate(TIMING_PHASES)}


def check_flight(doc: dict) -> None:
    """The shared /debug/flight schema both apiservers must satisfy
    (parity-pinned in tests/test_native_apiserver.py). Raises
    AssertionError on any violation."""
    assert isinstance(doc, dict), "flight dump is not an object"
    assert doc.get("server") in ("native", "mock"), doc.get("server")
    assert isinstance(doc["timing_enabled"], bool)
    assert isinstance(doc["ring_capacity"], int) and doc["ring_capacity"] > 0
    assert isinstance(doc["captured"], int) and doc["captured"] >= 0
    records = doc["records"]
    assert isinstance(records, list)
    assert len(records) <= doc["ring_capacity"]
    for rec in records:
        assert isinstance(rec["method"], str) and rec["method"]
        assert isinstance(rec["path"], str) and rec["path"]
        assert isinstance(rec["status"], int)
        assert rec["band"] in ("readonly", "mutating", "none"), rec["band"]
        assert isinstance(rec["ts_unix"], (int, float))
        assert isinstance(rec["total_us"], (int, float))
        assert rec["total_us"] >= 0
        phases = rec["phases_us"]
        assert set(phases) == set(TIMING_PHASES), sorted(phases)
        for v in phases.values():
            assert isinstance(v, (int, float)) and v >= 0


def check_watchers(doc: dict) -> None:
    """The shared ``GET /debug/watchers`` schema both apiservers must
    satisfy (parity-pinned in tests/test_native_apiserver.py, ISSUE 16):
    per-watcher ring-cursor lag, replay backlog, age, band, and the
    deterministic termination-risk classification. Raises AssertionError
    on any violation."""
    assert isinstance(doc, dict), "watchers dump is not an object"
    assert doc.get("server") in ("native", "mock"), doc.get("server")
    assert isinstance(doc["backlog_cap"], int) and doc["backlog_cap"] > 0
    assert isinstance(doc["thread_per_watcher"], bool)
    assert isinstance(doc["count"], int) and doc["count"] >= 0
    assert isinstance(doc["parked_threads"], int)
    assert 0 <= doc["parked_threads"] <= doc["count"]
    watchers = doc["watchers"]
    assert isinstance(watchers, list)
    assert len(watchers) == doc["count"]
    for w in watchers:
        assert w["kind"] in ("nodes", "pods"), w.get("kind")
        assert isinstance(w["lag_events"], int) and w["lag_events"] >= 0
        assert isinstance(w["replay_pending"], int)
        assert w["replay_pending"] >= 0
        assert isinstance(w["age_s"], (int, float)) and w["age_s"] >= 0
        assert w["band"] in ("readonly", "mutating", "none"), w.get("band")
        assert w["risk"] in ("none", "lagging", "at_risk"), w.get("risk")
        # the risk classification is a pure function of lag vs the
        # backlog cap — pinned here so both servers stay bit-identical
        lag = w["lag_events"]
        cap = doc["backlog_cap"]
        expect = (
            "none" if lag == 0
            else ("lagging" if lag <= cap // 2 else "at_risk")
        )
        assert w["risk"] == expect, (w["risk"], expect, lag, cap)


def lane_trace_events(
    lane_trace: dict, engine_epoch: float, index: int, pid: int
) -> list:
    """One lane child's span-ring dump as Chrome events under its own
    ``pid``, wall-aligned onto the parent engine's clock via each dump's
    ``otherData.epoch_unix`` stamp. A dump without the stamp CANNOT be
    aligned — refuse it loudly instead of merging garbage offsets."""
    other = lane_trace.get("otherData") or {}
    lane_epoch = other.get("epoch_unix")
    if not lane_epoch:
        raise ValueError(
            "lane trace dump has no otherData.epoch_unix wall anchor; "
            "cannot wall-align it with the engine trace (was it produced "
            "by an engine --trace-dump / /debug/trace?)"
        )
    shift_us = (float(lane_epoch) - engine_epoch) * 1e6
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"lane{index}"},
        }
    ]
    for ev in lane_trace.get("traceEvents") or ():
        ev = dict(ev)
        ev["pid"] = pid
        if "ts" in ev:
            ev["ts"] = round(float(ev["ts"]) + shift_us, 1)
        events.append(ev)
    return events


def flight_to_trace_events(
    flight: dict, epoch_unix: float, pid: int = 1
) -> list:
    """Chrome complete events for every flight record, with timestamps
    relative to ``epoch_unix`` (the engine tracer's wall anchor). Each
    request contributes one whole-request span on tid 0 plus one span
    per nonzero phase, laid out sequentially in reconciliation order
    (the flight ring keeps durations, not intra-request stamps);
    ``fanout`` overlays the commit window it is a subset of."""
    label = flight.get("server", "apiserver")
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"apiserver ({label})"},
        },
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "request"},
        },
    ]
    seen_lanes = set()
    for rec in flight.get("records", ()):
        ts = (rec["ts_unix"] - epoch_unix) * 1e6
        if ts < 0:
            continue  # predates the engine run: nothing to line up with
        events.append({
            "name": f'{rec["method"]} {rec["path"].split("?", 1)[0]}',
            "ph": "X",
            "ts": round(ts, 1),
            "dur": round(max(0.0, rec["total_us"]), 1),
            "pid": pid,
            "tid": 0,
            "cat": "apiserver",
            "args": {
                "status": rec["status"],
                "band": rec["band"],
                "path": rec["path"],
            },
        })
        cursor = ts
        for phase in SUM_PHASES:
            dur = float(rec["phases_us"].get(phase, 0.0))
            if dur <= 0:
                continue
            seen_lanes.add(phase)
            events.append({
                "name": phase,
                "ph": "X",
                "ts": round(cursor, 1),
                "dur": round(dur, 1),
                "pid": pid,
                "tid": _FLIGHT_LANES[phase],
                "cat": "apiserver",
            })
            if phase == "commit":
                fan = float(rec["phases_us"].get("fanout", 0.0))
                if fan > 0:
                    seen_lanes.add("fanout")
                    events.append({
                        "name": "fanout",
                        "ph": "X",
                        "ts": round(cursor, 1),
                        "dur": round(fan, 1),
                        "pid": pid,
                        "tid": _FLIGHT_LANES["fanout"],
                        "cat": "apiserver",
                    })
            cursor += dur
    for phase in sorted(seen_lanes):
        events.insert(2, {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": _FLIGHT_LANES[phase],
            "args": {"name": phase},
        })
    return events


def merge_timeline(
    engine_trace: dict, flight: dict, lane_traces=()
) -> dict:
    """One Chrome-trace document: the engine's span ring (pid 0, as
    dumped by ``--trace-dump`` / ``/debug/trace``), the apiserver's
    flight records (pid 1), and — with ``--lane-procs`` — each lane
    child's span-ring dump (pid 2+N), every tier wall-aligned via its
    own ``epoch_unix`` stamp. The sampled ``pod.ingest_to_patch`` spans
    carry ``{key, rv}`` args on both sides of the shm ring, so one
    Perfetto view follows a pod from raw wire bytes through a worker
    process to the apiserver commit."""
    check_flight(flight)
    epoch = float(
        (engine_trace.get("otherData") or {}).get("epoch_unix") or 0.0
    )
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 0,
            "tid": 0,
            "args": {"name": "engine"},
        }
    ]
    events += list(engine_trace.get("traceEvents") or ())
    events += flight_to_trace_events(flight, epoch, pid=1)
    for i, lane_trace in enumerate(lane_traces):
        events += lane_trace_events(lane_trace, epoch, i, pid=2 + i)
    other = dict(engine_trace.get("otherData") or {})
    other["flight_records_merged"] = len(flight.get("records") or ())
    other["flight_server"] = flight.get("server")
    if lane_traces:
        other["lane_traces_merged"] = len(lane_traces)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def attribution(flight: dict) -> dict:
    """Per-phase totals over a flight dump's records, with the phase-sum
    vs request-total reconciliation the latency gate enforces."""
    totals = {p: 0.0 for p in TIMING_PHASES}
    request_us = 0.0
    n = 0
    for rec in flight.get("records", ()):
        n += 1
        request_us += float(rec["total_us"])
        for p, v in rec["phases_us"].items():
            totals[p] += float(v)
    return _reconcile(totals, request_us, n)


_SAMPLE_RE = re.compile(
    r'^(kwok_apiserver_request_phase_seconds|kwok_apiserver_request_seconds)'
    r'_(sum|count)\{(?:phase|verb)="([a-z_]+)"\} (\S+)$'
)


def attribution_from_metrics(text: str) -> dict:
    """The same attribution table from a /metrics exposition scrape —
    the aggregate (histogram) view over every request the server ever
    timed, not just the flight ring's tail."""
    phase_sum = {p: 0.0 for p in TIMING_PHASES}
    phase_count = {p: 0 for p in TIMING_PHASES}
    request_us = 0.0
    requests = 0
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        fam, kind, label, value = m.groups()
        if fam.endswith("_phase_seconds"):
            if kind == "sum":
                phase_sum[label] += float(value)
            else:
                phase_count[label] += int(float(value))
        else:
            if kind == "sum":
                request_us += float(value)
            else:
                requests += int(float(value))
    out = _reconcile(
        {p: v * 1e6 for p, v in phase_sum.items()}, request_us * 1e6,
        requests,
    )
    out["phase_counts"] = phase_count
    return out


def _reconcile(totals_us: dict, request_us: float, n: int) -> dict:
    phase_sum = sum(totals_us[p] for p in SUM_PHASES)
    return {
        "requests": n,
        "phase_totals_us": {
            p: round(v, 3) for p, v in totals_us.items()
        },
        "phase_us_per_request": {
            p: round(v / n, 3) if n else 0.0
            for p, v in totals_us.items()
        },
        "phase_sum_us": round(phase_sum, 3),
        "request_total_us": round(request_us, 3),
        # in-handler glue the phases cannot see (band check, path match,
        # audit): the reconciliation residue the gate bounds
        "unattributed_us": round(request_us - phase_sum, 3),
        "unattributed_frac": round(
            (request_us - phase_sum) / request_us, 4
        ) if request_us else 0.0,
    }


def format_table(att: dict) -> str:
    """Human-readable attribution table (the CLI's --table output)."""
    n = att["requests"]
    lines = [
        f"requests: {n}",
        f"{'phase':>14s} {'total ms':>12s} {'us/request':>12s}",
    ]
    for p in TIMING_PHASES:
        total = att["phase_totals_us"].get(p, 0.0)
        per = att["phase_us_per_request"].get(p, 0.0)
        tag = " (subset of commit)" if p == "fanout" else ""
        lines.append(f"{p:>14s} {total / 1e3:12.3f} {per:12.3f}{tag}")
    lines.append(
        f"{'phase sum':>14s} {att['phase_sum_us'] / 1e3:12.3f}"
    )
    lines.append(
        f"{'request total':>14s} {att['request_total_us'] / 1e3:12.3f}"
    )
    lines.append(
        f"{'unattributed':>14s} {att['unattributed_us'] / 1e3:12.3f}"
        f"  ({att['unattributed_frac'] * 100:.1f}%)"
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="merge an engine --trace-dump with an apiserver "
        "/debug/flight dump into one Chrome-trace JSON"
    )
    p.add_argument("--trace", required=True,
                   help="engine Chrome-trace JSON (--trace-dump output "
                   "or a saved /debug/trace)")
    p.add_argument("--flight", required=True,
                   help="apiserver /debug/flight dump")
    p.add_argument("--lane-dump", action="append", default=[],
                   metavar="FILE",
                   help="a lane child's span-ring dump (--lane-procs "
                   "writes <trace>.lane<i>.json per lane); repeatable — "
                   "each merges as pid 2+N, wall-aligned via its "
                   "epoch_unix stamp")
    p.add_argument("--out", default="",
                   help="write the merged Chrome trace here")
    p.add_argument("--table", action="store_true",
                   help="print the per-phase attribution table")
    args = p.parse_args(argv)
    with open(args.trace) as f:
        trace = json.load(f)
    with open(args.flight) as f:
        flight = json.load(f)
    lane_traces = []
    for path in args.lane_dump:
        with open(path) as f:
            doc = json.load(f)
        if not (doc.get("otherData") or {}).get("epoch_unix"):
            p.error(
                f"--lane-dump {path}: no otherData.epoch_unix wall "
                "anchor; refusing to merge a dump that cannot be "
                "wall-aligned (use an engine --trace-dump / "
                "/debug/trace output)"
            )
        lane_traces.append(doc)
    try:
        merged = merge_timeline(trace, flight, lane_traces)
    except ValueError as e:
        p.error(str(e))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"merged trace: {args.out} "
              f"({len(merged['traceEvents'])} events)")
    if args.table or not args.out:
        print(format_table(attribution(flight)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
