import sys

from kwok_tpu.kwok.cli import main

sys.exit(main())
