"""tpukwok: the engine CLI (mirrors pkg/kwok/cmd/root.go + cmd/kwok/main.go).

Flag surface matches the reference (root.go:156-169); precedence is config
file < KWOK_* env < flags (config/flags.go:34-63 pattern: file values seed
the flag defaults, so unset flags inherit them).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading
import time

from kwok_tpu.config.stages import Stage, stages_to_rules
from kwok_tpu.config.types import (
    KwokConfiguration,
    apply_env_overrides,
    first_of,
    load_documents,
    parse_bool,
)
from kwok_tpu.models.lifecycle import ResourceKind

logger = logging.getLogger("kwok_tpu.kwok")

DEFAULT_CONFIG = os.path.expanduser("~/.kwok/kwok.yaml")


def build_parser(defaults) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpukwok",
        description="TPU-native fake kubelet: simulates node/pod lifecycle "
        "against a kube-apiserver with a batched device tick engine.",
    )
    o = defaults
    p.add_argument("--config", default=DEFAULT_CONFIG,
                   help="config file (multi-doc YAML, kwok.x-k8s.io/v1alpha1)")
    p.add_argument("--kubeconfig", default=os.environ.get("KUBECONFIG", ""))
    p.add_argument("--master", default="",
                   help="apiserver URL override (like kube --master); a "
                   "comma-separated list federates N apiservers onto one "
                   "stacked tick")
    p.add_argument("--member-config", action="append", default=[],
                   help="per-member kwok config YAML for --master "
                   "federation, repeatable and positional: the i-th flag "
                   "applies to the i-th master (its Stage documents "
                   "replace that member's lifecycle rules — heterogeneous "
                   "federation). An empty value inherits --config. Fewer "
                   "flags than masters: the remainder inherit.")
    p.add_argument("--cidr", default=o.cidr)
    p.add_argument("--node-ip", default=o.nodeIP)
    p.add_argument("--manage-all-nodes", type=_bool, default=o.manageAllNodes)
    p.add_argument("--manage-nodes-with-annotation-selector",
                   default=o.manageNodesWithAnnotationSelector)
    p.add_argument("--manage-nodes-with-label-selector",
                   default=o.manageNodesWithLabelSelector)
    p.add_argument("--disregard-status-with-annotation-selector",
                   default=o.disregardStatusWithAnnotationSelector)
    p.add_argument("--disregard-status-with-label-selector",
                   default=o.disregardStatusWithLabelSelector)
    p.add_argument("--server-address", default=o.serverAddress,
                   help="healthz/metrics address, e.g. 0.0.0.0:10247")
    p.add_argument("--enable-cni", type=_bool, default=o.enableCNI)
    p.add_argument("--tick-interval", type=float, default=o.tickInterval)
    p.add_argument("--tick-substeps", type=int, default=o.tickSubsteps,
                   help="simulated ticks fused into one device dispatch "
                   "(amortizes round-trips on remote/tunneled TPUs)")
    p.add_argument("--heartbeat-interval", type=float, default=o.heartbeatInterval)
    p.add_argument("--parallelism", type=int, default=o.parallelism)
    p.add_argument("--drain-shards", type=int, default=o.drainShards,
                   help="hash-partitioned host lanes for the drain+emit "
                   "pipeline: each lane runs its own ingest drain, emit "
                   "worker, and pump connection group so the host side "
                   "scales past one core (0 = auto: cpu_count capped by "
                   "--max-drain-shards; 1 = the classic single-lane "
                   "engine)")
    p.add_argument("--max-drain-shards", type=int, default=o.maxDrainShards,
                   help="cap on the AUTO --drain-shards lane count "
                   "(0 = built-in default, config.types."
                   "DEFAULT_MAX_DRAIN_SHARDS); explicit --drain-shards "
                   "values are never capped")
    p.add_argument("--lane-procs", type=_bool, default=o.laneProcs,
                   help="run each drain shard as a worker PROCESS over "
                   "shared-memory arenas instead of a thread (the GIL "
                   "escape): children own their shard's rows, device "
                   "tick, emit, and pump; the parent keeps watch ingest "
                   "+ the router and supervises respawns. Default off "
                   "(threaded lanes byte-unchanged); env KWOK_LANE_PROCS; "
                   "needs an HTTP --master, refused with --use-mesh / "
                   "--ha-role / federation")
    p.add_argument("--initial-capacity", type=int, default=o.initialCapacity)
    p.add_argument("--use-mesh", type=_bool, default=o.useMesh,
                   help="shard cluster state across all local devices")
    p.add_argument("--profile-dir", default="",
                   help="write a JAX profiler trace of ticks 2-102 here")
    p.add_argument("--trace-dump", default="",
                   help="write the engine's span trace (Chrome trace-event "
                   "JSON, same document as /debug/trace) here at stop; "
                   "KWOK_TPU_TRACE=<path> works too")
    p.add_argument("--trace-sample-every", type=int, default=256,
                   help="sample 1-in-N watch events for end-to-end "
                   "ingest->patch spans (0 disables)")
    p.add_argument("--faults", default=o.faults,
                   help="deterministic fault-injection spec "
                   "(docs/resilience.md grammar, e.g. "
                   "'seed=42;pump.drop=0.02;watch.expire=0.1'); "
                   "KWOK_TPU_FAULTS works too; empty = disabled "
                   "(zero overhead)")
    p.add_argument("--shed-queue-depth", type=int, default=o.shedQueueDepth,
                   help="shed routed events (kwok_dropped_jobs_total, "
                   "kwok_degraded, /readyz 503) when a lane queue is "
                   "deeper than this instead of growing it without "
                   "bound; 0 = never shed")
    p.add_argument("--worker-restart-budget", type=int,
                   default=o.workerRestartBudget,
                   help="watchdog: max restarts of one crashed lane "
                   "worker per --worker-restart-window before the "
                   "engine goes degraded")
    p.add_argument("--worker-restart-window", type=float,
                   default=o.workerRestartWindow,
                   help="watchdog restart-budget window in seconds")
    p.add_argument("--checkpoint-dir", default=o.checkpointDir,
                   help="crash-durable restarts: periodically checkpoint "
                   "the device-resident timer state (remaining Stage "
                   "delays, heartbeat phases) here via atomic rename; a "
                   "cold start re-lists then resumes matching rows' "
                   "timers from the file (docs/resilience.md). "
                   "KWOK_TPU_CHECKPOINT_DIR works too; empty = disabled "
                   "(no thread, no gathers)")
    p.add_argument("--checkpoint-interval", type=float,
                   default=o.checkpointInterval,
                   help="checkpoint cadence in seconds")
    p.add_argument("--audit-interval", type=float,
                   default=o.auditInterval,
                   help="anti-entropy auditor cadence in seconds: a paced "
                   "background pass diffs a budgeted window of apiserver "
                   "objects against engine rows by (uid, rv, phase), "
                   "classifies silent divergence (missed-event / "
                   "double-apply / stale-row / ghost-row) and repairs "
                   "per row via re-ingest (docs/resilience.md). "
                   "KWOK_TPU_AUDIT_INTERVAL works too; 0 = off "
                   "(no thread, no LISTs)")
    p.add_argument("--ha-role", default=o.haRole,
                   choices=["", "off", "primary", "standby"],
                   help="warm-standby HA (docs/resilience.md): 'primary' "
                   "serves while renewing the coordination.k8s.io Lease "
                   "and fences every outward write on still holding it; "
                   "'standby' runs observe-only (ingests warm, arms "
                   "nothing, emits nothing), tails the holder's "
                   "checkpoint stream, and takes over on lease expiry. "
                   "Empty = HA off (no elector thread, no fence). "
                   "KWOK_HA_ROLE works too")
    p.add_argument("--ha-identity", default=o.haIdentity,
                   help="lease holderIdentity AND this engine's "
                   "checkpoint file name (<dir>/<identity>.ckpt.json) "
                   "under HA; default hostname-pid")
    p.add_argument("--lease-name", default=o.leaseName,
                   help="coordination.k8s.io Lease object name the HA "
                   "pair elects through")
    p.add_argument("--lease-namespace", default=o.leaseNamespace)
    p.add_argument("--lease-duration", type=float,
                   default=o.leaseDuration,
                   help="lease TTL seconds (whole seconds on the wire): "
                   "the failure-detection budget — a dead primary is "
                   "unservable at most this long before the standby "
                   "may acquire")
    p.add_argument("--lease-renew-interval", type=float,
                   default=o.leaseRenewInterval,
                   help="leader renew cadence; 0 = lease-duration/3")
    p.add_argument("--drain-deadline", type=float,
                   default=o.drainDeadline,
                   help="SIGTERM graceful-drain bound: flush in-flight "
                   "emits and write a final checkpoint within this many "
                   "seconds, else force-exit nonzero (a second SIGTERM "
                   "force-exits immediately)")
    from kwok_tpu import log

    log.add_flags(p)
    return p


_bool = parse_bool


def _engine_config(args, stages: list[Stage]):
    from kwok_tpu.config.types import resolve_drain_shards
    from kwok_tpu.engine import EngineConfig

    return EngineConfig(
        drain_shards=resolve_drain_shards(
            args.drain_shards, args.max_drain_shards
        ),
        max_drain_shards=args.max_drain_shards,
        lane_procs=args.lane_procs,
        manage_all_nodes=args.manage_all_nodes,
        manage_nodes_with_annotation_selector=args.manage_nodes_with_annotation_selector,
        manage_nodes_with_label_selector=args.manage_nodes_with_label_selector,
        disregard_status_with_annotation_selector=args.disregard_status_with_annotation_selector,
        disregard_status_with_label_selector=args.disregard_status_with_label_selector,
        cidr=args.cidr,
        node_ip=args.node_ip,
        enable_cni=args.enable_cni,
        tick_interval=args.tick_interval,
        tick_substeps=args.tick_substeps,
        heartbeat_interval=args.heartbeat_interval,
        parallelism=args.parallelism,
        initial_capacity=args.initial_capacity,
        use_mesh=args.use_mesh,
        profile_dir=args.profile_dir,
        trace_dump=args.trace_dump,
        trace_sample_every=args.trace_sample_every,
        faults=args.faults,
        shed_queue_depth=args.shed_queue_depth,
        worker_restart_budget=args.worker_restart_budget,
        worker_restart_window=args.worker_restart_window,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_interval=args.checkpoint_interval,
        audit_interval=args.audit_interval,
        ha_role="" if args.ha_role == "off" else args.ha_role,
        ha_identity=args.ha_identity,
        lease_name=args.lease_name,
        lease_namespace=args.lease_namespace,
        lease_duration=args.lease_duration,
        lease_renew_interval=args.lease_renew_interval,
        node_rules=stages_to_rules(stages, ResourceKind.NODE),
        pod_rules=stages_to_rules(stages, ResourceKind.POD),
    )


def make_signal_handler(stop: threading.Event, force_exit=None):
    """First SIGTERM/SIGINT: set the stop event and let the graceful
    drain run (flush in-flight emit slots, write a final checkpoint). A
    SECOND SIGTERM means the operator wants out NOW: force-exit 130
    without waiting on the drain. Factored out so the escalation is unit
    testable without a subprocess."""
    force = force_exit if force_exit is not None else os._exit
    state = {"terms": 0}

    def handler(sig, frame=None):
        if sig == signal.SIGTERM:
            state["terms"] += 1
            if state["terms"] >= 2:
                force(130)
                return
        stop.set()

    return handler


def stop_with_deadline(
    stop_fns, deadline: float, force_exit=None
) -> None:
    """Run the shutdown callables under a wall-clock bound: a drain that
    wedges past ``deadline`` seconds force-exits nonzero instead of
    hanging the process manager's TERM->KILL escalation window."""
    force = force_exit if force_exit is not None else os._exit
    timer = threading.Timer(max(0.1, deadline), force, args=(3,))
    timer.daemon = True
    timer.start()
    try:
        for fn in stop_fns:
            fn()
    finally:
        timer.cancel()


def wait_for_apiserver(client, deadline_seconds: float = 120.0) -> None:
    """Exponential backoff until the apiserver answers (root.go:99-120)."""
    delay = 0.5
    deadline = time.time() + deadline_seconds
    while True:
        try:
            client.list("nodes", field_selector=None, label_selector=None)
            return
        except Exception as e:
            if time.time() > deadline:
                raise RuntimeError(f"apiserver not reachable: {e}") from e
            logger.info("waiting for apiserver: %s", e)
            time.sleep(delay)
            delay = min(delay * 2, 10)


def main(argv=None, stop_event: threading.Event | None = None) -> int:
    # KWOK_TPU_PLATFORM forces the jax platform (e.g. "cpu") — needed when
    # the engine runs as a subprocess on machines where a TPU plugin
    # overrides env-level platform selection and the chip is busy.
    plat = os.environ.get("KWOK_TPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    # pre-parse --config (flags.go:34-63: config parsed before cobra)
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--config", default=DEFAULT_CONFIG)
    pre_args, _ = pre.parse_known_args(argv)
    docs = load_documents(pre_args.config)
    conf = first_of(docs, KwokConfiguration) or KwokConfiguration()
    apply_env_overrides(conf.options)
    stages = [d for d in docs if isinstance(d, Stage)]

    args = build_parser(conf.options).parse_args(argv)
    from kwok_tpu import log

    log.setup(args.verbosity)

    from kwok_tpu.edge.httpclient import HttpKubeClient
    from kwok_tpu.engine import ClusterEngine
    from kwok_tpu.kwok.server import EngineServer

    if args.enable_cni:
        from kwok_tpu import cni

        if cni.load_from_env():
            logger.info("cni provider loaded from KWOK_TPU_CNI_PROVIDER")

    # --master takes a comma-separated list: N apiservers federate onto one
    # stacked mesh-sharded tick (BASELINE config 5, engine/federation.py)
    masters = [m.strip() for m in (args.master or "").split(",") if m.strip()]
    # validate BEFORE any network waiting: misconfiguration must fail fast
    if args.member_config and len(masters) < 2:
        raise SystemExit(
            "--member-config is a federation flag: it needs a multi-master "
            "--master list (use --config for a single cluster)"
        )
    if len(args.member_config) > len(masters):
        raise SystemExit(
            f"--member-config given {len(args.member_config)} times "
            f"for {len(masters)} masters"
        )
    for mc in args.member_config:
        if mc and not os.path.exists(mc):
            # a typo'd path must not silently fall back to default rules
            # (the member would quietly run a homogeneous federation)
            raise SystemExit(f"--member-config {mc}: no such file")
    if len(masters) > 1 and args.lane_procs:
        # a federation's members already shard the host across masters;
        # process lanes are the single-cluster GIL escape — refusing
        # beats nesting two sharding topologies nobody has gated
        raise SystemExit(
            "--lane-procs is a single-cluster flag; federation "
            "(multi-master --master) shards the host per member"
        )
    if len(masters) > 1 and args.ha_role not in ("", "off"):
        # a federation already tolerates member failures via the shared
        # watchdog (PR 7); the lease-fenced pair is a single-cluster
        # topology — refusing beats silently running an unfenced leader
        raise SystemExit(
            "--ha-role is a single-cluster flag; federation "
            "(multi-master --master) has its own member failover"
        )
    if len(masters) > 1:
        from kwok_tpu.engine import FederatedEngine

        clients = [
            HttpKubeClient.from_kubeconfig(args.kubeconfig or None, m)
            for m in masters
        ]
        # wait for all members concurrently: startup is bounded by ONE
        # backoff window, not N sequential ones
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(clients)) as pool:
            list(pool.map(wait_for_apiserver, clients))
        member_configs = None
        if args.member_config:
            member_configs = []
            for i, _ in enumerate(masters):
                path = (
                    args.member_config[i]
                    if i < len(args.member_config)
                    else ""
                )
                if path:
                    mdocs = load_documents(path)
                    mstages = [d for d in mdocs if isinstance(d, Stage)]
                    if not mstages:
                        # a file with no Stage docs (typo'd kind/apiVersion)
                        # must not silently run the default rules — same
                        # guard as a typo'd path
                        raise SystemExit(
                            f"--member-config {path}: no Stage documents"
                        )
                    member_configs.append(_engine_config(args, mstages))
                else:
                    member_configs.append(_engine_config(args, stages))
        engine = FederatedEngine(
            clients, _engine_config(args, stages),
            member_configs=member_configs,
        )
    else:
        client = HttpKubeClient.from_kubeconfig(
            args.kubeconfig or None, masters[0] if masters else None
        )
        # process lanes rebuild their own clients in the children from
        # the same kubeconfig (engine/proclanes.py _lane_spec)
        client.kubeconfig_path = args.kubeconfig or ""
        wait_for_apiserver(client)
        engine = ClusterEngine(client, _engine_config(args, stages))
    # liveness first, readiness after: the server comes up immediately
    # (so /healthz//livez probes never kill the process mid-warm-up) but
    # /readyz answers 503 until engine.start() finishes pre-compiling the
    # fused tick kernel — anything gating load on readiness (kwokctl
    # WaitReady, rigs) must not see "ready" while the serial tick lane
    # would still stall on first-dispatch compilation
    server = None
    if args.server_address:
        server = EngineServer(engine, args.server_address)
        server.start()
        logger.info("serving healthz/metrics on %s", args.server_address)

    engine.start()
    logger.info("engine started (managing %s)",
                "all nodes" if args.manage_all_nodes else "selected nodes")

    stop = stop_event or threading.Event()
    handler = make_signal_handler(stop)
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, handler)
        except ValueError:
            pass  # not main thread (tests)
    try:
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        # SIGTERM graceful drain: engine.stop() flushes in-flight device
        # ticks and emit queues and writes the final checkpoint; the
        # whole drain is bounded by --drain-deadline (and a second
        # SIGTERM skips it outright — see make_signal_handler)
        stop_fns = [engine.stop]
        if server:
            stop_fns.append(server.stop)
        stop_with_deadline(stop_fns, args.drain_deadline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
