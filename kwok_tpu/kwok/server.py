"""HTTP server: /healthz /readyz /livez + Prometheus /metrics + /debug/trace.

Mirrors Serve in pkg/kwok/cmd/root.go:173-202, with real engine telemetry
instead of only Go runtime collectors (SURVEY.md section 5.5: the counters
that matter are transitions/sec, patches/sec, tick latency, watch lag).

Engines that carry a telemetry registry (ClusterEngine, FederatedEngine)
serve the full labeled exposition — real histograms with ``_bucket``/
``_sum``/``_count`` series, per-shard labels under federation, and a
``kwok_build_info`` gauge — via their ``metrics_text()``. ``/debug/trace``
returns the span ring as Chrome trace-event JSON (open it in Perfetto /
``chrome://tracing``). Plain dict-``metrics`` objects (tests, stubs) fall
back to the legacy flat renderer below.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_METRIC_HELP = {
    "transitions_total": "Lifecycle phase transitions applied by the tick kernel",
    "status_patches_total": "Status patches sent to the apiserver",
    "heartbeats_total": "Node heartbeat patches sent",
    "deletes_total": "Pod deletes issued",
    "watch_events_total": "Watch events ingested",
    "watch_bookmarks_total": "BOOKMARK events consumed (rv advanced, no ingest)",
    "watch_relists_total": "Full re-lists performed by the watch loops",
    "ingest_drain_seconds_sum": "Tick-thread seconds applying ingested events",
    "ingest_parse_seconds_sum": "Seconds in the batched C++ line parser (subset of drain)",
    "pump_send_seconds_sum": "Executor seconds inside native pump batches",
    "pump_requests_total": "Requests shipped through the native pump",
    "patch_errors_total": "Patch/delete jobs that raised",
    "ticks_total": "Engine ticks executed",
    "tick_seconds_sum": "Total seconds spent in tick_once",
    "tick_seconds_last": "Duration of the most recent tick",
    "watch_lag_seconds": "Enqueue-to-processing delay of the slowest event in the last tick",
    "ingest_queue_depth": "Watch events waiting to be ingested",
    "nodes_managed": "Nodes currently managed",
    "pods_managed": "Pods currently tracked",
}


def _errors_block(engine=None) -> str:
    """Error-accounting families (swallowed-exception and worker-crash
    counters, telemetry/errors.py): process-global state no engine
    registry owns. Labeled samples, so appended ONLY to the labeled
    (registry) exposition path — the legacy flat path stays label-free
    by contract (its strict grammar oracle has no label parser). An
    engine that aggregates across worker processes (``--lane-procs``)
    supplies ``process_metrics_text`` and its fleet-wide totals win over
    this process's own share."""
    fleet_fn = getattr(engine, "process_metrics_text", None)
    if callable(fleet_fn):
        return fleet_fn()
    from kwok_tpu.telemetry import errors as telemetry_errors

    return telemetry_errors.render_nonempty()


def _process_block() -> str:
    """Standard process collector subset (user+sys CPU of this process),
    appended to both exposition paths."""
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        cpu = round(ru.ru_utime + ru.ru_stime, 2)
    except (ImportError, OSError):
        return ""
    return (
        "# HELP process_cpu_seconds_total Total user and system CPU time "
        "spent in seconds\n"
        "# TYPE process_cpu_seconds_total counter\n"
        f"process_cpu_seconds_total {cpu}\n"
    )


def render_metrics(metrics) -> str:
    """Render /metrics text. Accepts an engine carrying a telemetry
    registry (the full labeled exposition) or a flat name->value dict (the
    legacy surface; kept for stub engines and old tooling). The legacy
    path types strictly by suffix — ``*_total``/``*_sum`` are counters,
    everything else (including ``*_seconds_last``) is a gauge — so its
    output also passes the strict-parser oracle."""
    text_fn = getattr(metrics, "metrics_text", None)
    if callable(text_fn):
        return text_fn() + _errors_block(metrics) + _process_block()
    metrics = dict(getattr(metrics, "metrics", metrics))
    lines = []
    for name, value in sorted(metrics.items()):
        full = f"kwok_{name}"
        if name in _METRIC_HELP:
            lines.append(f"# HELP {full} {_METRIC_HELP[name]}")
        kind = "counter" if name.endswith(("_total", "_sum")) else "gauge"
        lines.append(f"# TYPE {full} {kind}")
        lines.append(f"{full} {value}")
    return "\n".join(lines) + "\n" + _process_block()


class EngineServer:
    def __init__(self, engine, address: str) -> None:
        host, _, port = address.rpartition(":")
        handler = self._make_handler(engine)
        self.httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def _make_handler(self, engine):
        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path == "/readyz":
                    # readiness is gated on engine warm-up (start()
                    # pre-compiles the fused tick kernel, seconds through a
                    # tunneled device); liveness endpoints stay 200 the
                    # whole time so restart probes don't kill the warm-up
                    if not getattr(engine, "ready", True):
                        # a RESTARTED engine is the dangerous case: it
                        # can look alive while its rows are still empty —
                        # the startup catch-up gate keeps readiness 503
                        # (reason startup_resync) until the first full
                        # re-list + checkpoint reconcile lands
                        reason = (
                            "startup_resync"
                            if getattr(
                                engine, "startup_resync_pending", False
                            )
                            else "engine warming up"
                        )
                        self.send_error(503, reason)
                        return
                    if getattr(engine, "degraded", False):
                        # degraded mode (resilience/policy.py): shedding
                        # load, out of worker restart budget, a downed
                        # checkpoint disk, or unrepaired drift — alive
                        # (/livez stays 200) but don't send it traffic;
                        # the active reasons ride the status line so a
                        # probe log names the cause without a scrape
                        # (kwok_degraded{reason=} has the full detail)
                        deg = getattr(engine, "_degradation", None)
                        reasons = ",".join(
                            getattr(deg, "reasons", ())
                        ) if deg is not None else ""
                        self.send_error(
                            503,
                            "engine degraded"
                            + (f": {reasons}" if reasons else ""),
                        )
                        return
                    body = b"ok"
                    ctype = "text/plain"
                elif self.path in ("/healthz", "/livez"):
                    body = b"ok"
                    ctype = "text/plain"
                elif self.path == "/metrics":
                    body = render_metrics(engine).encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/debug/trace":
                    trace_fn = getattr(engine, "trace_chrome", None)
                    if not callable(trace_fn):
                        self.send_error(404, "engine has no tracer")
                        return
                    body = json.dumps(trace_fn()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        return Handler

    def start(self) -> None:
        from kwok_tpu.workers import spawn_worker

        self._thread = spawn_worker(
            self.httpd.serve_forever, name="kwok-http"
        )

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
