"""The kwok engine binary: CLI + healthz/metrics server around ClusterEngine
(mirrors pkg/kwok/cmd + cmd/kwok)."""
