"""Spawn-only rule: multiprocessing without an explicit spawn context.

The engine is thread-rich long before any lane process exists (watch
threads, the patch executor, lane workers, the profiling sampler, pump
connection threads). ``fork`` duplicates the parent at a random
instant: every mutex another thread happens to hold — allocator locks
inside glibc, the GIL's own machinery, `logging`'s module lock, our
stage locks — is cloned LOCKED into a child that has no thread to ever
release it. That is the classic fork-after-threads deadlock, and on
Linux ``multiprocessing``'s default start method is ``fork``, so any
bare ``multiprocessing.Process(...)`` / ``mp.Queue()`` is a latent
deadlock that only fires under load.

The rule therefore flags every process-creating or IPC-creating call
made on the ``multiprocessing`` module itself (however imported), plus
``get_context()`` calls that do not pin the literal ``"spawn"`` —
the compliant shape is::

    ctx = multiprocessing.get_context("spawn")
    ctx.Process(...); ctx.Pipe(); ...

Calls on a context OBJECT are not flagged (the context was vetted where
it was created). ``shared_memory`` / ``resource_tracker`` /
``connection`` attribute access is fine — those create no process and
inherit no fork semantics.
"""

from __future__ import annotations

import ast

from kwok_tpu.analysis.core import Finding, Module, Rule

# multiprocessing-module attributes whose call creates a process or an
# IPC primitive bound to the ambient (platform-default: fork) context
_CTX_FACTORIES = frozenset({
    "Process", "Pool", "Queue", "SimpleQueue", "JoinableQueue", "Pipe",
    "Manager", "Event", "Lock", "RLock", "Semaphore", "BoundedSemaphore",
    "Condition", "Barrier", "Value", "Array",
})


class SpawnOnlyRule(Rule):
    name = "spawn-only"
    description = (
        "multiprocessing must go through get_context(\"spawn\"): the "
        "engine is thread-rich, and fork-after-threads clones held "
        "locks into the child (deadlock)"
    )

    def check_module(self, mod: Module):
        # names bound to the multiprocessing module in this file
        mp_names: set[str] = set()
        # names bound directly to context factories via from-imports
        direct: dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "multiprocessing":
                        mp_names.add(a.asname or "multiprocessing")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "multiprocessing":
                    for a in node.names:
                        if a.name in _CTX_FACTORIES or a.name == "get_context":
                            direct[a.asname or a.name] = a.name
        if not mp_names and not direct:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name
            ) and fn.value.id in mp_names:
                if fn.attr in _CTX_FACTORIES:
                    yield Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=(
                            f"{fn.value.id}.{fn.attr}(...) uses the "
                            "platform-default start method (fork on "
                            "Linux): fork-after-threads clones held "
                            "locks into the child — build it from "
                            'get_context("spawn") instead'
                        ),
                    )
                    continue
                if fn.attr == "get_context":
                    yield from self._check_get_context(mod, node)
            elif isinstance(fn, ast.Name) and fn.id in direct:
                target = direct[fn.id]
                if target == "get_context":
                    yield from self._check_get_context(mod, node)
                else:
                    yield Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=(
                            f"bare {target}(...) imported from "
                            "multiprocessing uses the platform-default "
                            "start method (fork on Linux) — build it "
                            'from get_context("spawn") instead'
                        ),
                    )

    def _check_get_context(self, mod: Module, node: ast.Call):
        ok = (
            len(node.args) == 1
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "spawn"
        )
        if not ok:
            yield Finding(
                rule=self.name, path=mod.rel, line=node.lineno,
                message=(
                    "get_context() without the literal \"spawn\": the "
                    "ambient/fork start method clones held locks into "
                    "the child (fork-after-threads deadlock under the "
                    "engine's thread population)"
                ),
            )
