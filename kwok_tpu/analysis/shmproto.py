"""Shm/IPC protocol rule: the seqlock, slot, and ring state machines.

The cross-process substrate (``engine/shm.py``) is lock-free by design —
its correctness is pure store ORDER. Each protocol class declares its
header slots as class-level int constants, and this rule recognizes the
protocol from those names (so fixtures and future twins are checked by
shape, not by file path):

* ``SEQ`` + ``LEN``  -> a **seqlock slab** (MetricsBank). Any method
  that stores into the payload must stamp ``hdr[SEQ]`` BEFORE the first
  payload/length store (readers back off on odd) and stamp it again
  AFTER the last one (even: consistent). ``torn_*`` fault twins are the
  deliberate exception: they must still open-stamp, and must NOT close —
  a torn writer that restamps even would hide exactly the crash the
  fault injects.
* ``STATE`` + ``LEN`` -> a **crash-replay slot** (InflightSlot). A
  payload-writing method must order ``state=0`` (disarm-first) ->
  payload -> ``len`` -> ``state=1``; a re-arm torn mid-copy then parks
  as "empty" instead of presenting state=1 over mixed bytes. ``torn_*``
  twins need only the disarm prefix.
* ``W`` + ``R``       -> an **SPSC byte ring** (RawRing). The producer
  must copy the payload BEFORE publishing the ``hdr[W]`` cursor, and —
  cross-file — any function that both writes the ring and ships the
  descriptor must call ``try_write`` before the send (the pipe is the
  second fence; a descriptor sent first could be consumed against
  unpublished bytes).

Local aliases are tracked (``hdr = self.arena.hdr`` / ``payload =
self.arena.payload`` is the idiom throughout shm.py), so stores through
the alias and through the full attribute chain both count.

**Single-writer-per-bank** rides the same rule: every store through a
``BANK_*`` field index anywhere in the tree must come from a declared
writer (``BANK_WRITERS``). The bank rows are the one shm plane with no
stamp protocol at all — their entire safety argument IS the writer set
(children own their row; the parent only zeroes the heartbeat on
respawn), so an undeclared writer is a protocol break even if the code
"works" today.
"""

from __future__ import annotations

import ast

from kwok_tpu.analysis.core import Finding, Module, Rule

# Declared StatusBank writers: outermost function name (optionally
# Class.method) -> allowed BANK_* fields; empty set = any field. Nested
# closures inherit their outermost def's entry (lane_proc_main's
# status_loop). Reads are always free.
BANK_WRITERS = {
    # the lane child owns its whole row (pid/heartbeat at entry, the
    # status_loop closure for everything else)
    "lane_proc_main": frozenset(),
    # the parent's respawn zeroes the dead incarnation's heartbeat so
    # the stall detector re-arms against the NEW child's first beat
    "ProcLaneSet._do_respawn": frozenset({"BANK_ALIVE_NS"}),
}

_PAYLOAD_NAMES = frozenset({"payload"})
_HDR_NAMES = frozenset({"hdr"})


def _attr_chain(expr) -> "list[str] | None":
    """Attribute/Name chain as names, outermost first: self.arena.hdr ->
    ['self', 'arena', 'hdr']."""
    parts: list = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        parts.reverse()
        return parts
    return None


class _ProtoClass:
    """A protocol class: which slots it declares and its kind."""

    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.slots: dict = {}
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                names = [
                    t.id for t in stmt.targets if isinstance(t, ast.Name)
                ]
                if (
                    isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, int)
                ):
                    for nm in names:
                        self.slots[nm] = stmt.value.value
                elif isinstance(stmt.value, ast.Tuple) and all(
                    isinstance(e, ast.Constant) for e in stmt.value.elts
                ):
                    # the `STATE, LEN = 0, 1` form
                    if len(names) == 0 and all(
                        isinstance(t, ast.Tuple) for t in stmt.targets
                    ):
                        for tup in stmt.targets:
                            for el, val in zip(tup.elts, stmt.value.elts):
                                if isinstance(el, ast.Name):
                                    self.slots[el.id] = val.value

    @property
    def kind(self) -> "str | None":
        s = self.slots
        if "SEQ" in s and "LEN" in s:
            return "seqlock"
        if "STATE" in s and "LEN" in s:
            return "slot"
        if "W" in s and "R" in s:
            return "ring"
        return None


class _Store:
    __slots__ = ("line", "slot", "value")

    def __init__(self, line, slot, value=None):
        self.line = line
        self.slot = slot    # 'payload' | a header slot name (SEQ/LEN/...)
        self.value = value  # constant stored, when it is one


def _method_stores(fn: ast.FunctionDef, slot_names) -> list:
    """Ordered header/payload stores in one method, through aliases or
    full chains. Nested defs are skipped (separate protocol actors)."""
    aliases: dict = {}   # local name -> 'hdr' | 'payload'
    stores: list = []

    def classify_base(expr) -> "str | None":
        chain = _attr_chain(expr)
        if chain is None:
            return None
        if len(chain) == 1:
            return aliases.get(chain[0])
        if chain[-1] in _HDR_NAMES:
            return "hdr"
        if chain[-1] in _PAYLOAD_NAMES:
            return "payload"
        return None

    def slot_of(index_expr) -> "str | None":
        chain = _attr_chain(index_expr)
        if chain is None:
            return None
        name = chain[-1]
        return name if name in slot_names else None

    def walk(node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                base = classify_base(node.value)
                if base is not None:
                    aliases[node.targets[0].id] = base
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    base = classify_base(tgt.value)
                    if base == "payload":
                        stores.append(_Store(node.lineno, "payload"))
                    elif base == "hdr":
                        slot = slot_of(tgt.slice)
                        if slot is not None:
                            val = (
                                node.value.value
                                if isinstance(node.value, ast.Constant)
                                else None
                            )
                            stores.append(
                                _Store(node.lineno, slot, val)
                            )
        for child in ast.iter_child_nodes(node):
            walk(child)

    for stmt in fn.body:
        walk(stmt)
    return stores


class ShmProtocolRule(Rule):
    name = "shm-protocol"
    description = (
        "seqlock/slot/ring store-order state machines in the shm "
        "substrate, plus the single-writer-per-bank ownership table"
    )

    def check_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                pc = _ProtoClass(node)
                kind = pc.kind
                if kind is None:
                    continue
                for stmt in node.body:
                    if isinstance(stmt, ast.FunctionDef):
                        yield from self._check_method(mod, pc, kind, stmt)
        yield from self._check_bank_writers(mod)
        yield from self._check_descriptor_order(mod)

    # ------------------------------------------------- per-method protocol

    def _check_method(self, mod, pc, kind, fn):
        stores = _method_stores(fn, pc.slots)
        payload = [s for s in stores if s.slot == "payload"]
        if not payload:
            return  # reads, resets, closes: no payload, no protocol step
        torn = fn.name.startswith("torn_")
        first_p = payload[0].line
        last_pl = max(
            s.line for s in stores if s.slot in ("payload", "LEN")
        )
        where = f"{pc.node.name}.{fn.name}"

        if kind == "seqlock":
            opens = [
                s for s in stores if s.slot == "SEQ" and s.line < first_p
            ]
            closes = [
                s for s in stores if s.slot == "SEQ" and s.line > last_pl
            ]
            if not opens:
                yield Finding(
                    mod.rel, first_p, self.name,
                    f"{where}: payload store without an odd seq stamp "
                    "before it — readers can consume a half-written "
                    "slab (stamp hdr[SEQ] first)",
                )
            if torn:
                if closes:
                    yield Finding(
                        mod.rel, closes[0].line, self.name,
                        f"{where}: a torn_* fault twin must NOT restamp "
                        "seq after the partial copy — the even stamp "
                        "would hide exactly the crash it injects",
                    )
            elif not closes:
                yield Finding(
                    mod.rel, last_pl, self.name,
                    f"{where}: payload/len stores are never closed with "
                    "an even seq stamp — the slab stays 'mid-write' "
                    "forever and every reader backs off",
                )
        elif kind == "slot":
            disarms = [
                s for s in stores
                if s.slot == "STATE" and s.line < first_p and s.value == 0
            ]
            if not disarms:
                yield Finding(
                    mod.rel, first_p, self.name,
                    f"{where}: payload store without state=0 disarm "
                    "before it — a re-arm torn mid-copy presents "
                    "state=1 over a mix of old and new bytes",
                )
            early_arm = [
                s for s in stores
                if s.slot == "STATE" and s.line < first_p and s.value == 1
            ]
            if early_arm:
                yield Finding(
                    mod.rel, early_arm[0].line, self.name,
                    f"{where}: state=1 before the payload copy — the "
                    "reader is told the slot is armed while the bytes "
                    "are still landing",
                )
            if not torn:
                lens = [
                    s for s in stores
                    if s.slot == "LEN" and s.line > first_p
                ]
                arms = [
                    s for s in stores
                    if s.slot == "STATE" and s.value == 1
                    and s.line > (lens[-1].line if lens else first_p)
                ]
                if not lens:
                    yield Finding(
                        mod.rel, first_p, self.name,
                        f"{where}: payload store with no length store "
                        "after it — the reader cannot bound the slice",
                    )
                if not arms:
                    yield Finding(
                        mod.rel, last_pl, self.name,
                        f"{where}: slot is never armed (state=1 after "
                        "payload+len) — the write can never be replayed",
                    )
        elif kind == "ring":
            early_w = [
                s for s in stores if s.slot == "W" and s.line < first_p
            ]
            if early_w:
                yield Finding(
                    mod.rel, early_w[0].line, self.name,
                    f"{where}: hdr[W] published before the payload copy "
                    "— the consumer's descriptor can reference bytes "
                    "that have not landed (copy-before-publish)",
                )
            if not torn and not any(
                s.slot == "W" and s.line > first_p for s in stores
            ):
                yield Finding(
                    mod.rel, first_p, self.name,
                    f"{where}: payload copied but hdr[W] never "
                    "published — the bytes are unreachable and the "
                    "ring leaks capacity",
                )

    # --------------------------------------------- single-writer-per-bank

    def _check_bank_writers(self, mod):
        # every `X[... BANK_FOO ...] = value` store, attributed to its
        # outermost enclosing def (closures inherit the owner)
        def owner_allows(owner: "str | None", field: str) -> bool:
            if owner is None:
                return False
            allowed = BANK_WRITERS.get(owner)
            if allowed is None:
                return False
            return not allowed or field in allowed

        def bank_field(index_expr) -> "str | None":
            for sub in ast.walk(index_expr):
                chain = _attr_chain(sub) if isinstance(
                    sub, (ast.Attribute, ast.Name)
                ) else None
                if chain and chain[-1].startswith("BANK_") and \
                        chain[-1] != "BANK_FIELDS":
                    return chain[-1]
            return None

        def walk_stmts(node, owner):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in node.body:
                    yield from walk_stmts(child, owner)
                return
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        field = bank_field(tgt.slice)
                        if field is not None and not owner_allows(
                            owner, field
                        ):
                            yield Finding(
                                mod.rel, node.lineno, self.name,
                                f"{owner or mod.modname} stores "
                                f"{field} but is not a declared bank "
                                "writer — the StatusBank is single-"
                                "writer-per-row (add it to "
                                "BANK_WRITERS only with an ownership "
                                "argument)",
                            )
            for child in ast.iter_child_nodes(node):
                yield from walk_stmts(child, owner)

        for stmt in mod.tree.body:
            if isinstance(stmt, ast.ClassDef):
                for meth in stmt.body:
                    if isinstance(
                        meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        qual = f"{stmt.name}.{meth.name}"
                        for child in meth.body:
                            yield from walk_stmts(child, qual)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in stmt.body:
                    yield from walk_stmts(child, stmt.name)

    # ---------------------------------------- copy-before-descriptor-send

    def _check_descriptor_order(self, mod):
        # any function calling both ring.try_write and a .send/._send:
        # the first ring write must precede the first descriptor send
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            writes, sends = [], []
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fnname = None
                if isinstance(sub.func, ast.Attribute):
                    fnname = sub.func.attr
                elif isinstance(sub.func, ast.Name):
                    fnname = sub.func.id
                if fnname == "try_write":
                    writes.append(sub.lineno)
                elif fnname in ("send", "_send"):
                    sends.append(sub.lineno)
            if writes and sends and min(sends) < min(writes):
                yield Finding(
                    mod.rel, min(sends), self.name,
                    f"{node.name}: descriptor sent before the ring "
                    "write — the pipe is the second fence; a consumer "
                    "can slice bytes the producer has not published "
                    "(call try_write first)",
                )
