"""Kernel-purity rule: nothing host-side inside the jitted tick.

The tick path (``ops/tick.py``, ``ops/pallas_tick.py``, the shard_map'd
variants) must stay a pure function of its inputs to stay fusable into one
XLA program: a Python side effect inside a traced function either runs at
trace time only (silent wrong behavior), forces a host callback (breaks
fusion and adds a device round-trip per tick), or both. This rule finds
the *kernel scope* — functions reachable from a ``jax.jit`` /
``shard_map`` / ``pl.pallas_call`` root via the module's call graph — and
flags host-side constructs inside it:

- wall-clock / RNG: ``time.*``, ``datetime.*``, Python ``random.*``,
  ``np.random.*`` (device RNG is ``jax.random``; the pallas kernel's
  counter hash is jnp-only)
- host I/O and side effects: ``print``, ``open``, ``input``, ``logging``/
  ``logger`` calls, ``os.environ``/``os.getenv``
- implicit transfers: ``.item()``, host ``np.*`` calls on traced values
- host callbacks: ``io_callback``, ``pure_callback``, ``host_callback``,
  ``jax.debug.callback``

Jit roots are found structurally: ``@jax.jit`` / ``@functools.partial(
jax.jit, ...)`` decorators, ``jax.jit(fn)`` / ``shard_map(fn, ...)`` /
``pl.pallas_call(kern, ...)`` call sites (following one level of
``functools.partial`` aliasing), and functions *returned* by a factory
whose result is passed to ``jax.jit`` (the ``jax.jit(self._build(cap))``
pattern).
"""

from __future__ import annotations

import ast

from kwok_tpu.analysis.core import Finding, Module, Rule

_HOST_MODULES = {"time", "datetime", "random", "np", "numpy", "os",
                 "logging", "logger"}
_HOST_CALLS = {"print", "open", "input"}
_CALLBACKS = {"io_callback", "pure_callback", "host_callback", "callback"}


def _terminal(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _root_of_attr(expr: ast.expr) -> str | None:
    """Leftmost name of a dotted chain: np.random.uniform -> np."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _is_jit_expr(expr: ast.expr) -> bool:
    """jax.jit / jit as a bare reference (decorator or partial arg)."""
    return _terminal(expr) == "jit"


def _jit_arg_names(call: ast.Call, aliases: dict) -> list[str]:
    """Function names rooted by this call if it is jax.jit(f)/shard_map(f)/
    pl.pallas_call(f). Follows partial aliases one level."""
    t = _terminal(call.func)
    if t not in ("jit", "shard_map", "pallas_call"):
        return []
    out = []
    for arg in call.args[:1]:
        name = None
        if isinstance(arg, ast.Name):
            name = aliases.get(arg.id, arg.id)
        elif isinstance(arg, ast.Call) and _terminal(arg.func) == "partial":
            if arg.args and isinstance(arg.args[0], ast.Name):
                name = arg.args[0].id
        elif isinstance(arg, ast.Call):
            # jax.jit(self._build(cap)): the factory's returned nested
            # functions become roots (handled by the caller via factory
            # name)
            name = _terminal(arg.func)
            if name is not None:
                out.append(("factory", name))
                continue
        if name is not None:
            out.append(("fn", name))
    return out


class _FnScope:
    def __init__(self, node, mod: Module) -> None:
        self.node = node
        self.mod = mod
        self.name = node.name
        self.calls: set[str] = set()          # names this fn calls
        self.returned_defs: set[str] = set()  # nested defs it returns


class KernelPurityRule(Rule):
    name = "kernel-purity"
    description = (
        "no Python side effects, host callbacks, RNG/time calls, or "
        "implicit transfers inside functions reachable from the jitted tick"
    )

    def check_module(self, mod: Module):
        # ---- collect every function (incl. nested), partial aliases,
        # and jit roots ------------------------------------------------
        fns: dict[str, list[_FnScope]] = {}
        aliases: dict[str, str] = {}
        roots: set[str] = set()
        factories: set[str] = set()

        def visit(node, enclosing: "_FnScope | None"):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = _FnScope(node, mod)
                fns.setdefault(node.name, []).append(scope)
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        roots.add(node.name)
                    elif isinstance(dec, ast.Call):
                        if _terminal(dec.func) == "partial" and any(
                            _is_jit_expr(a) for a in dec.args
                        ):
                            roots.add(node.name)
                for child in node.body:
                    visit(child, scope)
                return
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and _terminal(node.value.func) == "partial":
                args = node.value.args
                if args and isinstance(args[0], ast.Name):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            aliases[tgt.id] = args[0].id
            if isinstance(node, ast.Call):
                for kind, name in _jit_arg_names(node, aliases):
                    (roots if kind == "fn" else factories).add(name)
            if isinstance(node, ast.Return) and enclosing is not None:
                if isinstance(node.value, ast.Name):
                    enclosing.returned_defs.add(node.value.id)
            if enclosing is not None and isinstance(node, ast.Call):
                t = _terminal(node.func)
                if t:
                    enclosing.calls.add(t)
            for child in ast.iter_child_nodes(node):
                visit(child, enclosing)

        visit(mod.tree, None)

        # factories: jax.jit(self._build(...)) — the defs _build returns
        for fac in factories:
            for scope in fns.get(fac, []):
                roots.update(scope.returned_defs)

        if not roots:
            return

        # ---- reachability over the name-level call graph --------------
        reach: set[str] = set()
        frontier = [r for r in roots if r in fns]
        while frontier:
            name = frontier.pop()
            if name in reach:
                continue
            reach.add(name)
            for scope in fns.get(name, []):
                for callee in scope.calls:
                    target = aliases.get(callee, callee)
                    if target in fns and target not in reach:
                        frontier.append(target)

        # ---- impurity scan inside reachable bodies --------------------
        for name in sorted(reach):
            for scope in fns.get(name, []):
                yield from self._scan_body(mod, scope)

    def _scan_body(self, mod: Module, scope: _FnScope):
        qual = f"{mod.modname}.{scope.name}"

        def check_call(call: ast.Call):
            fn = call.func
            t = _terminal(fn)
            if isinstance(fn, ast.Name) and fn.id in _HOST_CALLS:
                return f"host call {fn.id}() inside jitted {qual}"
            if t == "item":
                return (
                    f".item() inside jitted {qual}: forces a device->host "
                    "transfer per element"
                )
            if t in _CALLBACKS:
                return (
                    f"host callback {t}() inside jitted {qual}: breaks "
                    "fusion with a host round-trip per tick"
                )
            if isinstance(fn, ast.Attribute):
                root = _root_of_attr(fn)
                if root in ("np", "numpy"):
                    return (
                        f"host numpy call {root}.{t}() inside jitted "
                        f"{qual}: implicit transfer/trace-time constant"
                    )
                if root in ("time", "datetime"):
                    return (
                        f"{root}.{t}() inside jitted {qual}: wall-clock "
                        "reads freeze at trace time"
                    )
                if root == "random":
                    return (
                        f"random.{t}() inside jitted {qual}: host RNG "
                        "freezes at trace time (use jax.random)"
                    )
                if root in ("logging", "logger"):
                    return f"logging call inside jitted {qual}"
                if root == "os" and t in ("getenv", "environ"):
                    return f"os.{t} read inside jitted {qual}"
            return None

        def walk(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return  # separate scope; reachable ones scan themselves
            if isinstance(node, ast.Call):
                msg = check_call(node)
                if msg:
                    yield Finding(mod.rel, node.lineno, self.name, msg)
            if isinstance(node, ast.Subscript):
                # os.environ["X"] without a call
                if (
                    isinstance(node.value, ast.Attribute)
                    and node.value.attr == "environ"
                    and _root_of_attr(node.value) == "os"
                ):
                    yield Finding(
                        mod.rel, node.lineno, self.name,
                        f"os.environ read inside jitted {qual}",
                    )
            for child in ast.iter_child_nodes(node):
                yield from walk(child)

        for stmt in scope.node.body:
            yield from walk(stmt)
