"""Runtime shm-protocol witness: instrumented banks/rings/slots.

The static rule (``analysis/shmproto.py``) proves the store ORDER the
source prescribes; this proves what the objects DO under load and under
the PR 17 fault kinds. While installed, every ``MetricsBank``,
``InflightSlot``, and ``RawRing`` method call is wrapped and checked
against the protocol's observable contract:

* **seq discipline** — a completed ``write`` must leave the slab with an
  EVEN seq strictly greater than before (monotone: a regressing stamp
  would re-expose a retired snapshot); a ``torn_write`` must leave it
  ODD (a torn twin that restamps even hides the very crash it injects;
  this is the "no even-stamped torn read" half of the contract).
* **no torn reads** — ``read`` may only return ``None`` or a payload
  some completed ``write`` actually published on that instance; a slab
  assembled from a torn prefix is the bug the seqlock exists to prevent.
* **slot outcome** — after ``arm``, ``peek`` returns exactly the armed
  bytes; after ``torn_arm``, the slot must park EMPTY (state 0, peek
  ``None``): the disarm-first ordering made observable.
* **ring publication** — a successful ``try_write`` must have advanced
  the W cursor past the blob before returning (publish-after-copy), and
  ``read(offset, length)`` must return byte-identical data to what was
  written at that offset.

Witnessing is per-process: a bank attached from another process has no
recorded publications, so its reads are only checked for protocol
invariants that need no history (parity, monotonicity). Enabled for the
proc-lane suite via the ``KWOK_TPU_SHM_WITNESS=1`` conftest fixture
(``make proc-check``); usable directly as::

    with witness_shm() as w:
        ...exercise banks/rings/slots...
    # fixture calls w.assert_clean() -> AssertionError with call stacks
"""

from __future__ import annotations

import threading

from kwok_tpu.analysis.witness import Violation, _stack

_STATE_ATTR = "_kwok_shm_witness"
_MAX_PUBLISHED = 64  # per instance; tests publish far fewer


class _InstanceState:
    """Per-object witness memory (publications + last stamps)."""

    __slots__ = ("published", "order", "armed", "ring", "last_even_seq")

    def __init__(self) -> None:
        self.published: set = set()   # bank payloads completed writes put out
        self.order: list = []         # publication order, for bounding
        self.armed: "bytes | None" = None
        self.ring: dict = {}          # offset -> bytes
        self.last_even_seq = 0

    def publish(self, payload: bytes) -> None:
        self.published.add(payload)
        self.order.append(payload)
        while len(self.order) > _MAX_PUBLISHED:
            old = self.order.pop(0)
            if old not in self.order:
                self.published.discard(old)


def _state(obj) -> _InstanceState:
    st = getattr(obj, _STATE_ATTR, None)
    if st is None:
        st = _InstanceState()
        setattr(obj, _STATE_ATTR, st)
    return st


class ShmWitness:
    """Protocol-outcome recorder for the shm substrate."""

    _installed: "ShmWitness | None" = None
    _originals: dict = {}

    def __init__(self) -> None:
        self._vio_lock = threading.Lock()
        self.violations: list = []

    def _violate(self, kind: str, message: str) -> None:
        with self._vio_lock:
            self.violations.append(
                Violation(kind, message, [("call site", _stack(3))])
            )

    # ------------------------------------------------------------ seqlock

    def on_write(self, orig, bank, payload: bytes) -> bool:
        hdr = bank.arena.hdr
        seq0 = int(hdr[bank.SEQ])
        ok = orig(bank, payload)
        if not ok:
            return ok
        seq1 = int(hdr[bank.SEQ])
        if seq1 % 2:
            self._violate(
                "seqlock-open",
                f"MetricsBank.write left seq odd ({seq1}): the slab "
                "reads as mid-write forever",
            )
        if seq1 <= seq0:
            self._violate(
                "seqlock-monotonic",
                f"MetricsBank.write moved seq {seq0} -> {seq1}: a "
                "non-advancing stamp re-exposes a retired snapshot",
            )
        st = _state(bank)
        st.publish(bytes(payload))
        st.last_even_seq = seq1
        return ok

    def on_torn_write(self, orig, bank, payload: bytes) -> None:
        orig(bank, payload)
        seq = int(bank.arena.hdr[bank.SEQ])
        if len(payload) <= bank.cap and seq % 2 == 0:
            self._violate(
                "torn-even-stamp",
                f"MetricsBank.torn_write left seq EVEN ({seq}): readers "
                "will consume the torn prefix as a consistent snapshot",
            )
        return None

    def on_read(self, orig, bank, *args, **kwargs):
        out = orig(bank, *args, **kwargs)
        st = getattr(bank, _STATE_ATTR, None)
        if out is not None and st is not None and st.published:
            if bytes(out) not in st.published:
                self._violate(
                    "torn-read",
                    "MetricsBank.read returned a payload no completed "
                    "write published (torn or interleaved slab of "
                    f"{len(out)}B)",
                )
        return out

    def on_reset(self, orig, bank) -> None:
        orig(bank)
        st = getattr(bank, _STATE_ATTR, None)
        if st is not None:
            st.published.clear()
            st.order.clear()
            st.last_even_seq = 0

    # --------------------------------------------------------------- slot

    def on_arm(self, orig, slot, payload: bytes) -> bool:
        ok = orig(slot, payload)
        st = _state(slot)
        if ok:
            st.armed = bytes(payload)
            hdr = slot.arena.hdr
            if int(hdr[slot.STATE]) != 1 or int(hdr[slot.LEN]) != len(
                payload
            ):
                self._violate(
                    "slot-arm",
                    "InflightSlot.arm returned True but the slot is not "
                    f"armed over {len(payload)}B (state="
                    f"{int(hdr[slot.STATE])}, len={int(hdr[slot.LEN])})",
                )
        return ok

    def on_torn_arm(self, orig, slot, payload: bytes) -> None:
        orig(slot, payload)
        if int(slot.arena.hdr[slot.STATE]) != 0:
            self._violate(
                "torn-armed",
                "InflightSlot.torn_arm left state != 0: a torn re-arm "
                "must park as empty (disarm-first ordering broken)",
            )
        return None

    def on_clear(self, orig, slot) -> None:
        orig(slot)
        st = getattr(slot, _STATE_ATTR, None)
        if st is not None:
            st.armed = None

    def on_peek(self, orig, slot):
        out = orig(slot)
        st = getattr(slot, _STATE_ATTR, None)
        if out is not None and st is not None and st.armed is not None:
            if bytes(out) != st.armed:
                self._violate(
                    "slot-peek",
                    "InflightSlot.peek returned bytes that differ from "
                    "the armed payload (replay would emit a torn batch)",
                )
        return out

    # --------------------------------------------------------------- ring

    def on_try_write(self, orig, ring, blob):
        off = orig(ring, blob)
        if off is None:
            return off
        st = _state(ring)
        st.ring[off] = bytes(blob)
        while len(st.ring) > _MAX_PUBLISHED:
            st.ring.pop(next(iter(st.ring)))
        w = int(ring.arena.hdr[ring.W])
        if w < off + len(blob):
            self._violate(
                "ring-publish",
                f"RawRing.try_write returned offset {off} but W={w} "
                f"< {off + len(blob)}: the descriptor outruns the "
                "published cursor",
            )
        return off

    def on_ring_read(self, orig, ring, offset: int, length: int):
        out = orig(ring, offset, length)
        st = getattr(ring, _STATE_ATTR, None)
        if st is not None and offset in st.ring:
            want = st.ring.pop(offset)
            if bytes(out) != want:
                self._violate(
                    "ring-torn-read",
                    f"RawRing.read({offset}, {length}) returned bytes "
                    "differing from the blob written at that offset",
                )
        return out

    def assert_clean(self) -> None:
        if self.violations:
            raise AssertionError(
                "shm-protocol witness recorded "
                f"{len(self.violations)} violation(s):\n\n"
                + "\n\n".join(v.format() for v in self.violations)
            )

    # ---------------------------------------------------------- installation

    @classmethod
    def install(cls) -> "ShmWitness":
        if cls._installed is not None:
            return cls._installed
        from kwok_tpu.engine import shm

        w = cls()
        cls._installed = w

        def wrap(klass, name, hook):
            orig = getattr(klass, name)
            cls._originals[(klass, name)] = orig

            def method(self, *args, **kwargs):
                return hook(orig, self, *args, **kwargs)

            method.__name__ = name
            setattr(klass, name, method)

        wrap(shm.MetricsBank, "write", w.on_write)
        wrap(shm.MetricsBank, "torn_write", w.on_torn_write)
        wrap(shm.MetricsBank, "read", w.on_read)
        wrap(shm.MetricsBank, "reset", w.on_reset)
        wrap(shm.InflightSlot, "arm", w.on_arm)
        wrap(shm.InflightSlot, "torn_arm", w.on_torn_arm)
        wrap(shm.InflightSlot, "clear", w.on_clear)
        wrap(shm.InflightSlot, "peek", w.on_peek)
        wrap(shm.RawRing, "try_write", w.on_try_write)
        wrap(shm.RawRing, "read", w.on_ring_read)
        return w

    @classmethod
    def uninstall(cls) -> None:
        if cls._installed is None:
            return
        for (klass, name), orig in cls._originals.items():
            setattr(klass, name, orig)
        cls._originals.clear()
        cls._installed = None


def witness_shm():
    """Context manager installing a witness (test helper). Joining an
    already-installed witness (the conftest fixture's) is allowed; only
    the installer uninstalls on exit."""

    class _Ctx:
        def __enter__(self):
            self._owner = ShmWitness._installed is None
            self.w = ShmWitness.install()
            return self.w

        def __exit__(self, *exc):
            if self._owner:
                ShmWitness.uninstall()

    return _Ctx()
