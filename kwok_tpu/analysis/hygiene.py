"""Exception-hygiene rule: no silent broad excepts.

A broad handler (``except Exception``, ``except BaseException``, or a bare
``except:``) that neither raises nor *does* anything observable — no call
(logging, counter bump, queue put, cleanup), just ``pass``/``continue``/
constant assignments — swallows failures invisibly. Those are exactly the
sites where the next soak-rig heisenbug hides (48 of them existed when
this rule landed). The fix is one of:

- narrow the exception type (an ``except ImportError`` fallback is fine)
- log it: ``logger.warning(..., exc_info=True)``
- count it: ``telemetry.errors.swallowed("site")`` — exported as
  ``kwok_swallowed_errors_total{site=...}``
- for the handful of genuinely-expected shutdown races (``__del__``
  safety nets), suppress with a justification:
  ``# kwoklint: disable=silent-except -- <why>``
"""

from __future__ import annotations

import ast

from kwok_tpu.analysis.core import Finding, Module, Rule

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Tuple):
        names = [e for e in t.elts]
    else:
        names = [t]
    for e in names:
        if isinstance(e, ast.Name) and e.id in _BROAD:
            return True
        if isinstance(e, ast.Attribute) and e.attr in _BROAD:
            return True
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """True when the handler body neither raises nor performs any call —
    i.e. the exception vanishes without a trace."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call)):
            return False
    return True


class SilentExceptRule(Rule):
    name = "silent-except"
    description = (
        "broad except handlers must log, count, re-raise, or carry a "
        "justified suppression"
    )

    def check_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _is_silent(node):
                yield Finding(
                    mod.rel, node.lineno, self.name,
                    "broad except swallows the exception silently: narrow "
                    "the type, log it (exc_info=True), or bump "
                    "telemetry.errors.swallowed(site)",
                )
