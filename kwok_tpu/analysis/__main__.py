"""kwoklint CLI: ``python -m kwok_tpu.analysis`` (``make analyze``).

Exit codes: 0 = clean, 1 = unsuppressed findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kwok_tpu.analysis.core import Analyzer, all_rules

#: disclosed runtime budget: analyze gates hack/verify-all.sh, so the
#: whole rule pack must stay comfortably interactive
BUDGET_S = 30.0


def repo_root() -> str:
    """The tree kwoklint ships in: two levels above this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kwok_tpu.analysis",
        description="kwoklint: concurrency + kernel-purity static analysis",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the kwok_tpu package)",
    )
    parser.add_argument(
        "--rule", action="append", default=None,
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--jsonl", action="store_true",
        help="machine-readable output: one JSON object per finding, then "
        "one {\"summary\": ...} line (overrides --format)",
    )
    parser.add_argument(
        "--timings", action="store_true",
        help="per-rule timing footer (text mode; always present in "
        "--jsonl summaries)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root for relative paths + docs (default: autodetected)",
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else repo_root()
    rules = all_rules(root)
    if args.list_rules:
        for r in rules:
            print(f"{r.name:22s} {r.description}")
        return 0
    if args.rule:
        known = {r.name for r in rules}
        bad = set(args.rule) - known
        if bad:
            print(
                f"unknown rule(s): {', '.join(sorted(bad))} "
                f"(known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 2
        rules = [r for r in rules if r.name in set(args.rule)]

    paths = args.paths or [os.path.join(root, "kwok_tpu")]
    paths = [os.path.abspath(p) for p in paths]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    analyzer = Analyzer(root, rules)
    findings, suppressed = analyzer.run(paths)
    timings = analyzer.timings
    total = sum(timings.values())
    if args.jsonl:
        for f in findings:
            print(json.dumps(vars(f), sort_keys=True))
        print(json.dumps({"summary": {
            "findings": len(findings),
            "suppressed": suppressed,
            "timings_s": {k: round(v, 4) for k, v in timings.items()},
            "total_s": round(total, 4),
            "budget_s": BUDGET_S,
        }}, sort_keys=True))
    elif args.format == "json":
        print(json.dumps(
            {
                "findings": [vars(f) for f in findings],
                "suppressed": suppressed,
                "timings_s": {k: round(v, 4) for k, v in timings.items()},
                "total_s": round(total, 4),
                "budget_s": BUDGET_S,
            },
            indent=1,
        ))
    else:
        for f in findings:
            print(f.format())
        tail = f"{len(findings)} finding(s), {suppressed} suppressed"
        print(f"kwoklint: {tail}" if findings else f"kwoklint: clean ({tail})")
        if args.timings:
            for name, secs in sorted(
                timings.items(), key=lambda kv: -kv[1]
            ):
                print(f"  {name:22s} {secs:7.3f}s")
            print(
                f"  {'total':22s} {total:7.3f}s "
                f"(budget {BUDGET_S:.0f}s — analyze gates verify-all and "
                "must stay fast)"
            )
        if total > BUDGET_S:
            print(
                f"kwoklint: WARNING: analysis took {total:.1f}s, over the "
                f"{BUDGET_S:.0f}s budget",
                file=sys.stderr,
            )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
