"""Native-twin lint bridge: lock discipline for ``native/*.cc``.

The C++ apiserver/pump carry the parity-pinned dialect (~6k lines) with
none of the Python tree's lint coverage. This module closes the gap with
a line-level parser (the approach ``metrics_doc.py`` already uses for
apiserver.cc metric strings): comments and string/raw-string literals
are stripped, brace depth is tracked, and every
``std::lock_guard``/``std::unique_lock`` declaration opens a lexical
critical section that ends with its enclosing brace. Three rules read
the resulting acquisition timeline:

- ``cc-lock-order`` — nested guard acquisitions must descend the
  declared table below; same-name nesting is a self-deadlock
  (``std::mutex`` is non-recursive) or an ABBA hazard across instances
  (shard locks never nest with each other by contract); the standalone
  mutexes must never share a lexical scope with any other guard.
- ``cc-fence-first`` — the server-side write fence (ISSUE 12): a
  deferred ``std::unique_lock<std::mutex> fence_lk;`` must be armed by
  ``fence_check(fence_lk)`` as the IMMEDIATELY following statement
  (check and commit are one critical section), and every
  ``commit_locked(`` reached under a shard lock must have the fence
  gate lexically in scope — a mutation handler that drops the fence
  loses zombie-primary write-deadness.
- ``cc-socket-under-lock`` — no socket write (``send``/``send_all``)
  while a store or shard mutex is lexically held: one slow client would
  convoy the whole store. The watch streamer's shape (drain under
  ``ring_mu``, send after the scope closes) is the compliant pattern.

The analysis is lexical (per-function scopes), deliberately: the
documented cross-function nestings (``commit_locked``'s registry
identity check under the caller's ``mu``) are invisible here and stay
the runtime witness's job. The declared table mirrors
``analysis/locks.py`` — the native store splits Python's ``_ring_lock``
(level 88) into ``mu`` (clock) and ``ring_mu`` (broadcast), declared
88/89 so the split keeps a total order.
"""

from __future__ import annotations

import glob
import os
import re

from kwok_tpu.analysis.core import Finding, Rule

# Declared C++ mutex order (outermost first), mirroring the Python table
# in analysis/locks.py: lease 86 -> shard 87 -> store clock 88 ->
# broadcast ring 89 -> audit 95. Names are the terminal identifier of
# the guard's mutex expression (`store.lease_mu` -> lease_mu,
# `sh->smu` -> smu).
CC_LOCK_ORDER: dict[str, int] = {
    "lease_mu": 86,
    "smu": 87,
    "mu": 88,
    "ring_mu": 89,
    "audit_mu": 95,
}

# Mutexes that must never share a lexical critical section with ANY
# other guard: shards_mu guards shard-registry creation/swap only;
# g_flight_mu and g_pumps_mu are microsecond registry lookups.
CC_STANDALONE: frozenset = frozenset({
    "shards_mu", "g_flight_mu", "g_pumps_mu",
})

# The store/shard set for the socket-write check (a send while one of
# these is held convoys every other request on the partition).
CC_STORE_LOCKS: frozenset = frozenset({
    "lease_mu", "smu", "mu", "ring_mu", "shards_mu",
})

# Socket-write calls (apiserver.cc send_all wraps send(2); pump.cc
# calls send(2) directly).
_SEND_RE = re.compile(r"(?<![\w.>])(?:send_all|send)\s*\(")

_GUARD_RE = re.compile(
    r"\b(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\s*<[^>]*>\s+"
    r"(\w+)\s*\(\s*([^)]*)\)"
)
_DEFERRED_RE = re.compile(
    r"\b(?:std::)?unique_lock\s*<[^>]*>\s+(\w+)\s*;"
)
_LATE_BIND_RE = re.compile(
    r"\b(\w+)\s*=\s*(?:std::)?unique_lock\s*<[^>]*>\s*\(\s*([^)]*)\)"
)
_FENCE_CALL_RE = re.compile(r"\bfence_check\s*\(\s*(\w+)\s*\)")
_FENCE_DEF_RE = re.compile(r"\bfence_check\s*=\s*\[")
_UNLOCK_RE = re.compile(r"\b(\w+)\s*\.\s*unlock\s*\(\s*\)")
_COMMIT_RE = re.compile(r"\bcommit_locked\s*\(")


def cc_files(root: str) -> list:
    """Every native C++ translation unit the bridge lints."""
    return sorted(
        glob.glob(os.path.join(root, "kwok_tpu", "native", "*.cc"))
    )


def _mutex_name(expr: str) -> "str | None":
    """Terminal identifier of a guard's mutex expression."""
    expr = expr.strip()
    if not expr:
        return None
    last = re.split(r"\.|->", expr)[-1].strip()
    return last if re.fullmatch(r"\w+", last) else None


def _strip_code(source: str) -> list:
    """Source -> per-line code with comments and string/char literals
    blanked (braces and parens inside them must not count). Handles
    ``//``, ``/* */``, ``"..."`` with escapes, ``'...'``, and raw
    strings ``R"delim( ... )delim"`` (the bootstrap-RBAC JSON blob spans
    dozens of brace-laden lines)."""
    out_lines = []
    buf = []
    state = "code"  # code | line_comment | block_comment | str | char | raw
    raw_end = ""
    i, n = 0, len(source)
    while i < n:
        c = source[i]
        if c == "\n":
            if state == "line_comment":
                state = "code"
            out_lines.append("".join(buf))
            buf = []
            i += 1
            continue
        if state == "code":
            if c == "/" and i + 1 < n and source[i + 1] == "/":
                state = "line_comment"
                i += 2
                continue
            if c == "/" and i + 1 < n and source[i + 1] == "*":
                state = "block_comment"
                i += 2
                continue
            m = re.match(r'R"([^\s()\\]{0,16})\(', source[i:i + 20]) \
                if c == "R" else None
            if m:
                state = "raw"
                raw_end = ")" + m.group(1) + '"'
                i += m.end()
                continue
            if c == '"':
                state = "str"
                i += 1
                continue
            if c == "'":
                state = "char"
                i += 1
                continue
            buf.append(c)
            i += 1
            continue
        if state in ("str", "char"):
            if c == "\\":
                i += 2
                continue
            if (state == "str" and c == '"') or (
                state == "char" and c == "'"
            ):
                state = "code"
            i += 1
            continue
        if state == "raw":
            if source.startswith(raw_end, i):
                state = "code"
                i += len(raw_end)
            else:
                i += 1
            continue
        if state == "block_comment":
            if c == "*" and i + 1 < n and source[i + 1] == "/":
                state = "code"
                i += 2
            else:
                i += 1
            continue
        i += 1  # line_comment
    if buf or state != "code":
        out_lines.append("".join(buf))
    return out_lines


class _Acq:
    """One lexical acquisition: mutex name + what was already held."""

    __slots__ = ("line", "mutex", "held", "var")

    def __init__(self, line, mutex, held, var):
        self.line = line
        self.mutex = mutex
        self.held = held  # [(mutex, line), ...] at acquisition time
        self.var = var


class _CcScan:
    """One parsed .cc file: acquisition timeline + rule-ready events."""

    def __init__(self, path: str, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.acquisitions: list = []   # _Acq
        self.sends: list = []          # (line, held-list)
        self.deferred_decls: list = [] # (line, var, next_code_line_text, next_line_no)
        self.commits: list = []        # (line, held-list, fence_in_scope)
        self._parse(_strip_code(source))

    def _parse(self, lines: list) -> None:
        depth = 0
        held: list = []      # [decl_depth, mutex, line, var]
        deferred: dict = {}  # var -> (decl_depth, line)
        pending_decl: "tuple | None" = None  # (line, var) awaiting next stmt
        # depth at which a `fence_check = [...]` lambda was defined:
        # commits are held to the fence requirement only while it is in
        # scope (the client request handler) — server-internal commits
        # (bootstrap seeding, event eviction) have no claim to check
        fence_def_depth: "int | None" = None

        def held_snapshot():
            return [(h[1], h[2]) for h in held]

        for lineno, line in enumerate(lines, 1):
            code = line.strip()
            if not code or code.startswith("#"):
                continue
            if pending_decl is not None:
                self.deferred_decls.append(
                    (pending_decl[0], pending_decl[1], code, lineno)
                )
                pending_decl = None

            # interleave guard/send/brace events by column so a guard
            # inside a one-line block scopes to that block's braces
            events: list = []  # (pos, kind, payload)
            for m in _GUARD_RE.finditer(line):
                name = _mutex_name(m.group(2))
                if name is not None:
                    events.append((m.start(), "acq", (name, m.group(1))))
            for m in _DEFERRED_RE.finditer(line):
                events.append((m.start(), "defer", m.group(1)))
            for m in _LATE_BIND_RE.finditer(line):
                name = _mutex_name(m.group(2))
                if name is not None:
                    events.append((m.start(), "bind", (name, m.group(1))))
            for m in _FENCE_CALL_RE.finditer(line):
                events.append((m.start(), "fence", m.group(1)))
            for m in _FENCE_DEF_RE.finditer(line):
                events.append((m.start(), "fence_def", None))
            for m in _UNLOCK_RE.finditer(line):
                events.append((m.start(), "unlock", m.group(1)))
            for m in _SEND_RE.finditer(line):
                events.append((m.start(), "send", None))
            for m in _COMMIT_RE.finditer(line):
                events.append((m.start(), "commit", None))
            for i, ch in enumerate(line):
                if ch in "{}":
                    events.append((i, ch, None))
            events.sort(key=lambda ev: ev[0])

            for _pos, kind, payload in events:
                if kind == "{":
                    depth += 1
                elif kind == "}":
                    depth = max(0, depth - 1)
                    held[:] = [h for h in held if h[0] <= depth]
                    deferred = {
                        v: dv for v, dv in deferred.items()
                        if dv[0] <= depth
                    }
                    if fence_def_depth is not None \
                            and depth < fence_def_depth:
                        fence_def_depth = None
                elif kind == "acq":
                    name, var = payload
                    self.acquisitions.append(
                        _Acq(lineno, name, held_snapshot(), var)
                    )
                    held.append([depth, name, lineno, var])
                elif kind == "defer":
                    deferred[payload] = (depth, lineno)
                    pending_decl = (lineno, payload)
                elif kind == "bind":
                    name, var = payload
                    self.acquisitions.append(
                        _Acq(lineno, name, held_snapshot(), var)
                    )
                    d = deferred.get(var, (depth, lineno))[0]
                    held.append([d, name, lineno, var])
                elif kind == "fence":
                    # fence_check(fence_lk) binds lease_mu to the
                    # deferred lock when the request carries a fence
                    # claim: model it as acquiring lease_mu at the
                    # declaration's scope
                    var = payload
                    if var in deferred:
                        self.acquisitions.append(
                            _Acq(lineno, "lease_mu", held_snapshot(), var)
                        )
                        held.append(
                            [deferred[var][0], "lease_mu", lineno, var]
                        )
                elif kind == "unlock":
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][3] == payload:
                            del held[i]
                            break
                elif kind == "fence_def":
                    fence_def_depth = depth
                elif kind == "send":
                    self.sends.append((lineno, held_snapshot()))
                elif kind == "commit":
                    self.commits.append(
                        (lineno, held_snapshot(),
                         fence_def_depth is not None)
                    )


# parse cache: (path, mtime) -> _CcScan; three rules share one parse
_scan_cache: dict = {}


def scan_cc(path: str, root: str) -> _CcScan:
    key = (path, os.path.getmtime(path))
    hit = _scan_cache.get(path)
    if hit is not None and hit[0] == key[1]:
        return hit[1]
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    scan = _CcScan(path, os.path.relpath(path, root), source)
    _scan_cache[path] = (key[1], scan)
    return scan


class _CcRuleBase(Rule):
    """Shared .cc discovery: lints kwok_tpu/native/*.cc under the repo
    root, or an explicit directory/file list (fixture tests)."""

    def __init__(self, cc_paths: "list | None" = None) -> None:
        self.cc_paths = cc_paths

    def _scans(self, root: str):
        paths = self.cc_paths if self.cc_paths is not None \
            else cc_files(root)
        for p in paths:
            yield scan_cc(p, root)


class CcLockOrderRule(_CcRuleBase):
    name = "cc-lock-order"
    description = (
        "native guards follow the declared mutex order lease_mu(86) -> "
        "smu(87) -> mu(88) -> ring_mu(89); standalone mutexes never "
        "share a scope"
    )

    def check_project(self, mods, root):
        for scan in self._scans(root):
            for acq in scan.acquisitions:
                for held_name, held_line in acq.held:
                    msg = self._violation(held_name, acq.mutex)
                    if msg:
                        yield Finding(
                            scan.rel, acq.line, self.name,
                            f"{msg} (outer acquired at line {held_line})",
                        )

    @staticmethod
    def _violation(held: str, inner: str) -> "str | None":
        if inner == held:
            return (
                f"re-acquires {inner} while already holding it: "
                "std::mutex is non-recursive (self-deadlock), and two "
                "instances of one lock class have no defined order "
                "(ABBA hazard)"
            )
        if held in CC_STANDALONE or inner in CC_STANDALONE:
            alone = held if held in CC_STANDALONE else inner
            return (
                f"acquires {inner} while holding {held}: {alone} is "
                "declared standalone and must never share a critical "
                "section with another guard"
            )
        lh = CC_LOCK_ORDER.get(held)
        li = CC_LOCK_ORDER.get(inner)
        if lh is None or li is None:
            return None
        if li < lh:
            return (
                f"acquires {inner} (level {li}) while holding {held} "
                f"(level {lh}): out of declared native lock order"
            )
        return None


class CcFenceFirstRule(_CcRuleBase):
    name = "cc-fence-first"
    description = (
        "a deferred fence lock is armed by fence_check() as the first "
        "statement of its critical section, and commit_locked under a "
        "shard lock requires the fence gate in scope"
    )

    def check_project(self, mods, root):
        for scan in self._scans(root):
            for line, var, next_code, next_line in scan.deferred_decls:
                want = re.compile(
                    r"if\s*\(\s*!\s*fence_check\s*\(\s*" + re.escape(var)
                    + r"\s*\)\s*\)"
                )
                if not want.search(next_code):
                    yield Finding(
                        scan.rel, line, self.name,
                        f"deferred lock {var} is not armed by "
                        f"`if (!fence_check({var}))` as the immediately "
                        "following statement: the fence claim check must "
                        "be the FIRST statement of the mutation critical "
                        "section (check+commit atomic, ISSUE 12)",
                    )
            for line, held, fenced_scope in scan.commits:
                names = {h for h, _l in held}
                if fenced_scope and "smu" in names \
                        and "lease_mu" not in names:
                    yield Finding(
                        scan.rel, line, self.name,
                        "commit_locked under a shard lock without the "
                        "fence gate in scope: a mutation handler that "
                        "drops fence_check loses zombie-primary "
                        "write-deadness (declare a deferred fence lock "
                        "and arm it first)",
                    )


class CcSocketUnderLockRule(_CcRuleBase):
    name = "cc-socket-under-lock"
    description = (
        "no socket write (send/send_all) while a store or shard mutex "
        "is held"
    )

    def check_project(self, mods, root):
        for scan in self._scans(root):
            for line, held in scan.sends:
                bad = [
                    (h, l) for h, l in held if h in CC_STORE_LOCKS
                ]
                if bad:
                    locks = ", ".join(
                        f"{h} (line {l})" for h, l in bad
                    )
                    yield Finding(
                        scan.rel, line, self.name,
                        f"socket write while holding {locks}: one slow "
                        "client convoys every request on the partition "
                        "— drain under the lock, send after the scope "
                        "closes",
                    )
