"""Metrics-contract rule: the telemetry surface and the docs agree.

Every metric family the process can export must be catalogued in
``docs/observability.md``, and every ``kwok_*``/``process_*`` family the
doc catalogues must exist in code — a dashboard built from the doc must
never scrape a phantom, and a family added in code must never ship
undocumented. Label sets are also checked for consistency: one family
registered twice with different literal label tuples is a runtime
``ValueError`` waiting for the second registration to run.

Registered names come from two scans:

- literal first arguments of ``.counter(`` / ``.gauge(`` / ``.histogram(``
  calls anywhere in the tree (federation's aggregates, build info)
- all string constants in the registration surface — ``telemetry/``,
  ``kwok/server.py`` — which catches the dict-driven registrations
  (``_HELP`` / ``_COUNTERS`` in ``engine_metrics.py``) and the process
  collector the HTTP server appends
- the native apiserver's exposition source (``native/apiserver.cc``
  ``metrics_text()``): every ``kwok_*`` name in that file must be
  catalogued too, so native-side families can't drift undocumented
  (the C++ twin mirrors ``telemetry/apiserver_metrics.py``, but a
  family added only in the .cc would otherwise be invisible here)
"""

from __future__ import annotations

import ast
import os
import re

from kwok_tpu.analysis.core import Finding, Module, Rule

# Family names: kwok_* (must not end in '_' — docs use `kwok_lane_*`
# wildcards) plus the one process collector the HTTP server appends.
# Chrome-trace metadata strings (process_name/thread_name) stay out.
_NAME_RE = re.compile(
    r"\b(?:kwok_[a-z0-9_]*[a-z0-9]|process_cpu_seconds_total)\b"
)
_REG_METHODS = ("counter", "gauge", "histogram")
# files whose string constants are treated as the registration surface
_SURFACE = ("telemetry" + os.sep, os.path.join("kwok", "server.py"))
_SUFFIXES = ("_bucket", "_count", "_sum")


class MetricsContractRule(Rule):
    name = "metrics-doc"
    description = (
        "every registered metric family appears in docs/observability.md "
        "and vice versa; label sets are consistent across registrations"
    )

    def __init__(self, doc_path: str) -> None:
        self.doc_path = doc_path

    def check_project(self, mods: list[Module], root: str):
        registered: dict[str, tuple] = {}  # name -> (rel, line)
        labels: dict[str, dict] = {}       # name -> {labels tuple: (rel, line)}

        def note(name: str, rel: str, line: int) -> None:
            registered.setdefault(name, (rel, line))

        for mod in mods:
            surface = any(s in mod.rel for s in _SURFACE)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr in _REG_METHODS and node.args:
                    first = node.args[0]
                    if isinstance(first, ast.Constant) and isinstance(
                        first.value, str
                    ) and _NAME_RE.fullmatch(first.value):
                        note(first.value, mod.rel, node.lineno)
                        lab = self._literal_labels(node)
                        if lab is not None:
                            prev = labels.setdefault(first.value, {})
                            prev.setdefault(lab, (mod.rel, node.lineno))
                elif surface and isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    for m in _NAME_RE.findall(node.value):
                        note(m, mod.rel, node.lineno)

        # native exposition surface: kwok_* names in apiserver.cc. Only
        # QUOTED string literals are scanned — comments routinely carry
        # `kwok_tpu/...` path references that would otherwise register a
        # phantom family. A histogram family's _bucket/_sum/_count sample
        # names fold into their parent via the same suffix rule the doc
        # side uses.
        cc_path = os.path.join(root, "kwok_tpu", "native", "apiserver.cc")
        if os.path.exists(cc_path):
            cc_rel = os.path.relpath(cc_path, root)
            cc_str = re.compile(r'"((?:[^"\\]|\\.)*)"')
            with open(cc_path, encoding="utf-8") as fh:
                for i, line in enumerate(fh, 1):
                    for lit in cc_str.findall(line):
                        for m in _NAME_RE.findall(lit):
                            for suf in ("_bucket", "_count", "_sum"):
                                if m.endswith(suf):
                                    m = m[: -len(suf)]
                                    break
                            note(m, cc_rel, i)

        # label-set consistency across literal registrations
        for name, sets in labels.items():
            if len(sets) > 1:
                variants = sorted(sets.items())
                (rel, line) = variants[1][1]
                yield Finding(
                    rel, line, self.name,
                    f"{name} registered with inconsistent label sets: "
                    + " vs ".join(str(list(k)) for k, _ in variants),
                )

        if not os.path.exists(self.doc_path):
            yield Finding(
                os.path.relpath(self.doc_path, root), 1, self.name,
                "metric catalogue document is missing",
            )
            return
        with open(self.doc_path, encoding="utf-8") as fh:
            doc_lines = fh.read().splitlines()
        doc_rel = os.path.relpath(self.doc_path, root)
        documented: dict[str, int] = {}
        for i, line in enumerate(doc_lines, 1):
            for m in _NAME_RE.findall(line):
                documented.setdefault(m, i)

        def base(name: str) -> str:
            for suf in _SUFFIXES:
                if name.endswith(suf) and name[: -len(suf)] in registered:
                    return name[: -len(suf)]
            return name

        for name, (rel, line) in sorted(registered.items()):
            if name not in documented:
                yield Finding(
                    rel, line, self.name,
                    f"metric {name} is registered/exported but not "
                    f"catalogued in {doc_rel}",
                )
        for name, line in sorted(documented.items()):
            if base(name) not in registered:
                yield Finding(
                    doc_rel, line, self.name,
                    f"metric {name} is catalogued in the doc but "
                    "registered nowhere in the tree",
                )

    @staticmethod
    def _literal_labels(call: ast.Call) -> "tuple | None":
        """The label-names argument when fully literal (positional third
        arg or label_names kwarg), else None."""
        cand = None
        if len(call.args) >= 3:
            cand = call.args[2]
        for kw in call.keywords:
            if kw.arg == "label_names":
                cand = kw.value
        if cand is None:
            return None
        if isinstance(cand, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in cand.elts
        ):
            return tuple(e.value for e in cand.elts)
        return None
