"""kwoklint: the repo-native static-analysis suite (ISSUE 4 tentpole).

PR 2 made the engine genuinely concurrent — 15+ locks, per-lane worker
threads, a router/tick/emit topology — while the tick path is a JAX kernel
that must stay pure to stay fusable. The reference KWOK leans on Go's race
detector and ``go vet`` for exactly this class of code; this package is the
Python-side equivalent, purpose-built around the invariants the engine
actually depends on:

- ``locks``     — lock discipline against the declared lock-order table
                  (out-of-order nested acquisitions, blocking calls held
                  under a lock, locks created but never acquired)
- ``purity``    — no host side effects inside the jitted tick kernels
- ``hygiene``   — no silent broad ``except`` (swallows must log or count)
- ``metrics_doc`` — the telemetry surface and docs/observability.md agree

Run it as ``python -m kwok_tpu.analysis`` (``make analyze``). Findings are
``file:line: severity [rule] message``; suppress one with an inline
``# kwoklint: disable=<rule> -- <justification>`` comment (the
justification is mandatory — a bare suppression is itself a finding).

The runtime complement is ``witness`` — an instrumented Lock/RLock that
records acquisition-order edges during tests and fails on order-graph
cycles or declared-order violations with both stacks
(``KWOK_TPU_LOCK_WITNESS=1``, wired into ``make lane-check``).
"""

from kwok_tpu.analysis.core import (
    Analyzer,
    Finding,
    Rule,
    all_rules,
    load_module,
)

__all__ = ["Analyzer", "Finding", "Rule", "all_rules", "load_module"]
