"""Shared-state ownership rule: who mutates what, from which thread.

Every concurrency review since PR 12 hand-caught the same bug class —
an instance attribute mutated from two worker threads with no lock (the
``note_fenced`` unlocked ``+=``). This rule machine-checks it:

1. **Thread roots** come from the real spawn topology: every
   ``workers.spawn_worker``/``Watchdog.spawn`` call with a resolvable
   target and a literal (or locally-resolvable f-string) ``name``
   becomes a root — the tick loop, router, per-lane drain/emit workers,
   supervisor, checkpointer, chaos arms, watch threads. Local
   ``def spawn(target, name)`` forwarder closures (lanes/proclanes
   ``start_workers``) are followed, including the
   ``(lane.drain_loop, f"kwok-lane{i}")`` tuple-literal pairs they
   iterate. ``multiprocessing`` targets are deliberately NOT roots: a
   child process shares no objects, so cross-process "races" on
   instance attrs are impossible by construction (the shm protocol rule
   owns that plane).
2. **Reachability** is solved over the same interprocedural call graph
   the lock rules use (``locks.build_index``): a method reachable from
   two roots runs on two threads. Methods reachable from no spawn root
   are charged to the pseudo-root ``main`` (the caller's thread —
   start/stop/dispatch surface).
3. Every ``self.<attr>`` assign/augmented-assign in the engine's
   concurrent classes (``TARGET_CLASSES``) is classified by the roots
   reaching its enclosing method and whether it sits inside a declared
   lock region (``with <lock>:`` — the table in ``locks.py``).
   ``__init__`` is construction-before-threads and exempt.
4. An attr mutated from >= 2 distinct roots with at least one mutation
   site outside any lock region is a finding at each unlocked site —
   unless the module annotates it::

       # kwoklint: lockfree=<attr>[,<attr>...] -- <why this is safe>

   One annotation covers every mutation site of those attrs in its
   module. The justification is mandatory (a bare annotation is itself
   a finding) and annotations must stay live: one naming an attr this
   rule no longer flags is stale and reported, exactly like a stale
   suppression.

The per-instance sharding idiom falls out naturally: all per-lane
drain workers share one root identity (``kwok-lane*``), so a ShardLane
attr touched only by its own drain worker counts one root and stays
clean, while an attr the router also writes counts two.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize

from kwok_tpu.analysis.core import Finding, Module, Rule
from kwok_tpu.analysis.locks import (
    RECEIVER_CLASS_HINTS,
    _COMMON_NAMES,
    _classify_call,
    _terminal,
    build_index,
    is_lock_name,
)

# The engine's concurrent classes (the issue's list + the pump group):
# instance attrs of these are reachable from multiple worker threads.
TARGET_CLASSES = frozenset({
    "ClusterEngine",
    "ShardLane",
    "LaneSet",
    "ProcLaneSet",
    "Degradation",
    "Watchdog",
    "_PumpGroup",
    "_SlotGuardPump",
})

MAIN_ROOT = "main"

_LOCKFREE_RE = re.compile(
    r"#\s*kwoklint:\s*lockfree=([A-Za-z0-9_,]+)\s*(.*)$"
)

_SPAWN_NAMES = frozenset({"spawn_worker", "spawn"})

# locks.RECEIVER_CLASS_HINTS extended with the engine's plane handles:
# spawn targets like `self._ha.run` / `self._auditor.run` resolve through
# the receiver attr, and the `loop` local in ClusterEngine.start is
# assigned from `self._proc.coordinator_loop` / `self._lanes.tick_loop`.
_RECEIVER_HINTS = {
    **RECEIVER_CLASS_HINTS,
    "_ha": "HAPlane",
    "_auditor": "AntiEntropyAuditor",
    "_proc": "ProcLaneSet",
    "_lanes": "LaneSet",
}


class _Annotation:
    __slots__ = ("line", "attrs", "justification", "used")

    def __init__(self, line, attrs, justification):
        self.line = line
        self.attrs = attrs
        self.justification = justification
        self.used: set = set()  # attrs that silenced a finding


def scan_lockfree(mod: Module) -> list:
    """All `# kwoklint: lockfree=` annotations in a module (tokenize,
    not line-regex: markers inside string literals must not count)."""
    out = []
    try:
        for tok in tokenize.generate_tokens(
            io.StringIO(mod.source).readline
        ):
            if tok.type != tokenize.COMMENT:
                continue
            m = _LOCKFREE_RE.search(tok.string)
            if not m:
                continue
            attrs = tuple(
                a.strip() for a in m.group(1).split(",") if a.strip()
            )
            just = m.group(2).strip().lstrip("-—:· ").strip()
            out.append(_Annotation(tok.start[0], attrs, just))
    except tokenize.TokenError:
        pass
    return out


class _Mutation:
    __slots__ = ("cls", "attr", "line", "locked", "mod", "fi", "root")

    def __init__(self, cls, attr, line, locked, mod, fi, root=None):
        self.cls = cls
        self.attr = attr
        self.line = line
        self.locked = locked
        self.mod = mod
        self.fi = fi       # owning _FuncInfo (None for closure roots)
        self.root = root   # fixed root name for closure-body mutations


def _walk_mutations(body, on_mutation, lock_depth: int = 0) -> None:
    """Statement walk recording `self.<attr>` stores, tracking whether a
    declared lock (`with <lock>:`) is held. Nested defs are separate
    scopes (closures are handled as spawn roots, not here)."""

    def walk(node, locks: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            inner = locks
            for item in node.items:
                if is_lock_name(_terminal(item.context_expr)):
                    inner += 1
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                _record(tgt, node.lineno, locks)
        elif isinstance(node, ast.AugAssign):
            _record(node.target, node.lineno, locks)
        for child in ast.iter_child_nodes(node):
            walk(child, locks)

    def _record(tgt, line, locks) -> None:
        if isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                _record(el, line, locks)
            return
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            on_mutation(tgt.attr, line, locks > 0)

    for stmt in body:
        walk(stmt, lock_depth)


def _name_from_expr(expr, local_names: dict) -> "str | None":
    """A spawn's `name=` value as a root identity: literal string,
    f-string (formatted parts become `*`), or a local variable with
    exactly one such assignment in the function."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(expr, ast.Name):
        return local_names.get(expr.id)
    return None


class _Root:
    """One thread identity: a spawn name pattern + its entry points."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.entries: list = []    # _FuncInfo entry points
        self.closures: list = []   # (owner_fi, FunctionDef) closure bodies

    def __repr__(self) -> str:
        return f"<root {self.name}>"


def _resolve_spawn_target(index, fi, expr, closures: dict):
    """A spawn target expression -> ('fi', _FuncInfo) | ('closure',
    FunctionDef) | None."""
    if isinstance(expr, ast.Attribute):
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            hit = index._resolve_in_class(fi.cls, expr.attr)
            return ("fi", hit) if hit is not None else None
        rname = _terminal(recv)
        if rname in _RECEIVER_HINTS:
            hit = index._resolve_in_class(_RECEIVER_HINTS[rname], expr.attr)
            if hit is not None:
                return ("fi", hit)
        if expr.attr in _COMMON_NAMES:
            return None
        cands = index.by_name.get(expr.attr, [])
        return ("fi", cands[0]) if len(cands) == 1 else None
    if isinstance(expr, ast.Name):
        if expr.id in closures:
            return ("closure", closures[expr.id])
        hit = index.by_module.get(fi.mod.modname, {}).get(expr.id)
        if hit is not None:
            return ("fi", hit)
        if expr.id in _COMMON_NAMES:
            return None
        cands = index.by_name.get(expr.id, [])
        return ("fi", cands[0]) if len(cands) == 1 else None
    return None


def _is_spawn_call(call: ast.Call, wrappers: set) -> "str | None":
    """'direct' for spawn_worker(...)/wd.spawn(...), 'wrapper' for a
    call to a local forwarder closure, else None."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "spawn_worker":
            return "direct"
        if fn.id in wrappers:
            return "wrapper"
        return None
    if isinstance(fn, ast.Attribute) and fn.attr == "spawn":
        # Watchdog.spawn delegates to spawn_worker with the same name
        return "direct"
    return None


def discover_roots(index) -> dict:
    """Spawn-site scan -> {root_name: _Root}. See module docstring for
    the shapes handled."""
    roots: dict = {}

    def root_for(name: "str | None") -> "_Root | None":
        if not name:
            return None
        return roots.setdefault(name, _Root(name))

    for fi in index.funcs:
        # nested defs (closure targets + spawn forwarders)
        closures = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.FunctionDef) and node is not fi.node:
                closures[node.name] = node
        wrappers = set()
        for cname, cnode in closures.items():
            for sub in ast.walk(cnode):
                if isinstance(sub, ast.Call) and _is_spawn_call(
                    sub, set()
                ) == "direct":
                    wrappers.add(cname)
                    break
        # local `name = "..."` / f-string constants (watch-thread names)
        # and `loop = self._lanes.tick_loop`-style callable locals (the
        # kwok-tick target is whichever branch assigned `loop`; all
        # assignments count — a conservative union of entry points)
        local_names: dict = {}
        local_callables: dict = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    v = _name_from_expr(node.value, {})
                    if v is not None and tgt.id not in local_names:
                        local_names[tgt.id] = v
                    if isinstance(node.value, ast.Attribute):
                        local_callables.setdefault(tgt.id, []).append(
                            node.value
                        )

        saw_variable_wrapper_call = False
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            kind = _is_spawn_call(node, wrappers)
            if kind is None:
                continue
            if kind == "direct":
                target = node.args[0] if node.args else None
                name_expr = next(
                    (kw.value for kw in node.keywords if kw.arg == "name"),
                    None,
                )
            else:  # wrapper: spawn(target, name) positional
                target = node.args[0] if len(node.args) >= 1 else None
                name_expr = node.args[1] if len(node.args) >= 2 else None
            if target is None:
                continue
            name = _name_from_expr(name_expr, local_names) \
                if name_expr is not None else None
            if name is None:
                if kind == "wrapper":
                    saw_variable_wrapper_call = True
                continue
            resolutions = []
            resolved = _resolve_spawn_target(index, fi, target, closures)
            if resolved is not None:
                resolutions.append(resolved)
            elif isinstance(target, ast.Name):
                for expr in local_callables.get(target.id, ()):
                    hit = _resolve_spawn_target(index, fi, expr, closures)
                    if hit is not None:
                        resolutions.append(hit)
            if not resolutions:
                continue
            r = root_for(name)
            for res in resolutions:
                if res[0] == "fi":
                    r.entries.append(res[1])
                else:
                    r.closures.append((fi, res[1]))
        if saw_variable_wrapper_call:
            # `for target, name in ((lane.drain_loop, f"kwok-lane{i}"),
            # ...): spawn(target, name)` — pair up the tuple literals
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Tuple)
                    and len(node.elts) == 2
                    and isinstance(node.elts[0], ast.Attribute)
                ):
                    name = _name_from_expr(node.elts[1], local_names)
                    if name is None:
                        continue
                    resolved = _resolve_spawn_target(
                        index, fi, node.elts[0], closures
                    )
                    if resolved is not None and resolved[0] == "fi":
                        root_for(name).entries.append(resolved[1])
    return roots


def solve_reachability(index, roots: dict) -> dict:
    """{_FuncInfo: set(root names)} over the resolved call graph."""
    reach: dict = {}
    for root in roots.values():
        frontier: list = list(root.entries)
        for owner_fi, cnode in root.closures:
            for sub in ast.walk(cnode):
                if isinstance(sub, ast.Call):
                    site = _classify_call(sub)
                    if site is None:
                        continue
                    for callee in index.resolve(owner_fi, site):
                        frontier.append(callee)
        seen = set()
        while frontier:
            fi = frontier.pop()
            if id(fi) in seen:
                continue
            seen.add(id(fi))
            reach.setdefault(fi, set()).add(root.name)
            for site in fi.calls:
                for callee in index.resolve(fi, site):
                    if id(callee) not in seen:
                        frontier.append(callee)
    return reach


class SharedStateRule(Rule):
    name = "shared-state"
    description = (
        "an instance attr of a concurrent engine class mutated from "
        ">=2 thread roots outside a lock region needs a lock or a "
        "justified `# kwoklint: lockfree=` annotation"
    )

    def check_project(self, mods, root):
        index = build_index(mods)
        roots = discover_roots(index)
        reach = solve_reachability(index, roots)

        # collect mutation sites in target classes
        mutations: list = []
        for fi in index.funcs:
            if fi.cls not in TARGET_CLASSES or fi.name == "__init__":
                continue

            def on_mut(attr, line, locked, fi=fi):
                mutations.append(_Mutation(
                    fi.cls, attr, line, locked, fi.mod, fi
                ))

            _walk_mutations(fi.node.body, on_mut)
        # closure-root bodies owned by a target class (the tick loop)
        for rname, r in roots.items():
            for owner_fi, cnode in r.closures:
                if owner_fi.cls not in TARGET_CLASSES:
                    continue

                def on_mut(attr, line, locked, owner_fi=owner_fi,
                           rname=rname):
                    mutations.append(_Mutation(
                        owner_fi.cls, attr, line, locked,
                        owner_fi.mod, None, root=rname,
                    ))

                _walk_mutations(cnode.body, on_mut)

        # aggregate per (class, attr)
        by_attr: dict = {}
        for m in mutations:
            by_attr.setdefault((m.cls, m.attr), []).append(m)

        annotations = {m.rel: scan_lockfree(m) for m in mods}
        by_rel = {m.rel: m for m in mods}
        findings: list = []
        for (cls, attr), sites in sorted(by_attr.items()):
            site_roots = set()
            for m in sites:
                if m.root is not None:
                    site_roots.add(m.root)
                else:
                    site_roots |= reach.get(m.fi, set()) or {MAIN_ROOT}
            unlocked = [m for m in sites if not m.locked]
            if len(site_roots) < 2 or not unlocked:
                continue
            names = ", ".join(sorted(site_roots))
            for m in unlocked:
                ann = next(
                    (a for a in annotations.get(m.mod.rel, ())
                     if attr in a.attrs),
                    None,
                )
                if ann is not None:
                    ann.used.add(attr)
                    continue
                where = m.fi.qual if m.fi is not None \
                    else f"{m.mod.modname}.{cls} (worker closure)"
                findings.append(Finding(
                    m.mod.rel, m.line, self.name,
                    f"{cls}.{attr} is mutated from threads [{names}] "
                    f"and this store in {where} holds no lock: take a "
                    "declared lock or annotate the module with "
                    f"`# kwoklint: lockfree={attr} -- <why>`",
                ))

        # annotation hygiene: justification mandatory, liveness required
        for rel, anns in annotations.items():
            mod = by_rel[rel]
            for a in anns:
                if not a.justification:
                    findings.append(Finding(
                        mod.rel, a.line, self.name,
                        "lockfree annotation without a justification "
                        "(write `# kwoklint: lockfree=<attr> -- <why>`)",
                    ))
                stale = [x for x in a.attrs if x not in a.used]
                if stale and not any(x in a.used for x in a.attrs):
                    findings.append(Finding(
                        mod.rel, a.line, self.name,
                        "lockfree annotation matched no multi-thread "
                        f"unlocked mutation ({', '.join(stale)}): "
                        "stale — remove it or fix the attr list",
                    ))
        return findings
