"""Lock-discipline rules: the declared order table, out-of-order nested
acquisitions, blocking calls held under a lock, and dead locks.

The engine's declared lock order (outermost first):

    stage_lock (lanes)  ->  _alloc_lock (engine)  ->  _gen_lock (engine)
        ->  leaves (pump group locks, _conns_lock, ippool/_registry/_audit
            "_lock" leaves, telemetry child locks)

A thread may only acquire DOWNWARD (strictly increasing level); two locks
at the same level have no declared order and must never nest; re-acquiring
the same lock is only legal for the RLocks (``stage_lock``, the
mockserver store lock). Analysis is interprocedural within the analyzed
tree: a ``with lock:`` body's calls are resolved (self/bases, same-module
functions, and package-unique method names) and their transitive
acquisitions and blocking calls are charged to the holding block, with the
call chain in the finding message.

"Blocking" is a curated list of the calls that actually stall this
codebase — thread joins, queue/event waits, socket and native-pump I/O,
apiserver round-trips, CNI provider calls, pump construction — not a
general effect system. A blocking call that is *by design* guarded by its
own leaf lock (e.g. the pump group lock exists to serialize sends on one
connection group) carries a justified suppression at the call site, which
also stops the call from propagating through transitive analysis.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from kwok_tpu.analysis.core import Finding, Module, Rule

# Declared order levels: smaller acquires first (outermost). Names not in
# the table are generic leaves at DEFAULT_LEVEL.
LOCK_ORDER: dict[str, int] = {
    "stage_lock": 10,
    "_alloc_lock": 20,
    "_gen_lock": 30,
    "lock": 80,         # _PumpGroup per-connection-group locks
    "_conns_lock": 80,  # httpclient keep-alive pool
    # resilience leaves (ISSUE 6): each guards one module's bookkeeping
    # dict/set and NOTHING is ever acquired under it — registry child
    # access always happens after release (see Degradation.set/clear,
    # FaultPlane.record, Watchdog._allow). Level 84: above the generic
    # single-resource leaves so holding one while (incorrectly) taking a
    # registry `_lock` would be an order VIOLATION, not an unordered pair.
    "_fault_lock": 84,  # FaultPlane: injected-fault tally + killer state
    "_deg_lock": 84,    # Degradation: the active-reasons set
    "_wd_lock": 84,     # Watchdog: restart stamps + restart log
    # checkpoint/startup-gate bookkeeping (ISSUE 7): marks RESYNC
    # completion per kind/lane — taken by drain workers (under their
    # lane's stage_lock, a legal 10 -> 84 descent) and the tick thread;
    # nothing is ever acquired under it
    "_ckpt_lock": 84,
    # apiserver overload admission (ISSUE 8): guards only the per-band
    # inflight/rejected counters in mockserver._Admission; the band SLOT
    # is held across the request but the lock is released immediately, so
    # nothing (store lock included) is ever acquired under it. Level 84 so
    # holding it into a level-85 leaf (the store's _lock, a registry
    # child) would be an order violation, not an unordered pair.
    "_adm_lock": 84,
    # anti-entropy auditor (ISSUE 10): guards only the scan cursor /
    # cycle-seen sets / unrepaired-streak dict in
    # resilience/antientropy.py — the audit thread's state, snapshot-read
    # by gates/tests. Taken after a lane's stage_lock on the pool-keys
    # walk (a legal 10 -> 84 descent); nothing is ever acquired under it.
    "_ae_lock": 84,
    # HA leadership plane (ISSUE 12): guards only the elector's role
    # state machine (leading/lost flags) and the tailed peer-checkpoint
    # document in resilience/ha.py. The fence itself is a lock-free
    # float attribute (the per-write check must never take a lock);
    # degradation/registry/_ckpt_lock interactions all happen AFTER
    # release — nothing is ever acquired under it.
    "_ha_lock": 84,
    # process lanes (ISSUE 15): guards only the lane-handle swap
    # (proc/conn references) between the supervisor's respawn and
    # close() in engine/proclanes.py. Spawning, joining, pipe sends, and
    # the shm ring writes all run OUTSIDE it; the ring itself is
    # lock-free (SPSC: int64 cursor stores are atomic, descriptors ride
    # the pipe). Nothing is ever acquired under it.
    "_proc_lock": 84,
    # MetricsBank fold/merge (ISSUE 16): guards only the retired-counter
    # baseline fold + freshest-lane-snapshot dict when a lane exits and
    # when the parent's /metrics scrape merges — shm seqlock reads and
    # plain dict folds run inside, so a concurrent scrape can never
    # double-count a dying lane's final snapshot. Nothing is ever
    # acquired under it (registry merge happens on a detached copy).
    "_mbank_lock": 84,
    "_lock": 85,        # single-resource leaves (ippool, registry, ...)
    "_apiserver_lock": 85,
    # mock-apiserver sharded store (ISSUE 13), outermost-first:
    # _lease_lock wraps a FENCED write's whole mutation (check+commit one
    # critical section, so a takeover PATCH cannot interleave), each
    # (kind, namespace) _shard_lock orders same-key writes, and
    # _ring_lock is the store's clock/broadcast section (revision
    # allocation, watch cache, undo log, serialize-once ring, watch
    # registry). Shard locks NEVER nest with each other — cross-shard
    # reads walk shards sequentially and reconcile via the undo log.
    "_lease_lock": 86,
    "_shard_lock": 87,
    "_ring_lock": 88,
    "_audit_lock": 95,  # mockserver audit ring, below the store lock
}
DEFAULT_LEVEL = 85

_LOCK_NAME_RE = re.compile(r"(^|_)lock$")

# Receivers whose zero-arg .get() means a blocking queue pop (dict.get
# always takes an argument, so zero-arg get is queue-shaped anyway; the
# name filter keeps obviously non-queue receivers out).
_QUEUEISH = re.compile(r"(^|_)(q|eq|queue)$")

# Receiver-name type hints: kwoklint is repo-native, so it may know the
# engine's naming conventions — `e`/`engine`/`parent` hold ClusterEngines
# in lanes/federation, `lane` holds a ShardLane. Lets `e._emit(...)` under
# a lock resolve even though `_emit` is not package-unique.
RECEIVER_CLASS_HINTS: dict[str, str] = {
    "e": "ClusterEngine",
    "engine": "ClusterEngine",
    "parent": "ClusterEngine",
    "lane": "ShardLane",
}

# Method names too common to resolve by package-wide uniqueness (stdlib
# collisions would mis-bind them to unrelated classes).
_COMMON_NAMES = frozenset({
    "get", "put", "close", "stop", "start", "run", "send", "read", "write",
    "join", "wait", "render", "grow", "flush", "items", "keys", "values",
    "pop", "add", "discard", "observe", "inc", "set", "labels", "acquire",
    "release", "update", "append", "clear", "copy", "submit", "shutdown",
    "next", "count", "index", "sum", "min", "max", "list", "dict", "sort",
})

_BLOCKING_ATTRS = frozenset({
    "sendall", "send_ordered", "recv", "connect", "accept", "getresponse",
    "request", "patch_status", "patch_meta", "read_batch", "result",
})


def lock_level(name: str) -> int:
    return LOCK_ORDER.get(name, DEFAULT_LEVEL)


def _terminal(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def is_lock_name(name: "str | None") -> bool:
    return bool(name) and bool(_LOCK_NAME_RE.search(name))


def blocking_reason(call: ast.Call) -> str | None:
    """Why this call may block, or None. Curated for this codebase."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "sleep":
            return "sleep()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    recv = fn.value
    recv_name = _terminal(recv)
    if attr == "sleep" and recv_name == "time":
        return "time.sleep()"
    if attr == "join":
        # str.join / os.path.join are pure; thread/process joins block
        if isinstance(recv, ast.Constant):
            return None
        if recv_name in ("os", "posixpath", "ntpath", "path"):
            return None
        return f"{recv_name or '?'}.join()"
    if attr == "get":
        if any(kw.arg == "timeout" for kw in call.keywords):
            return f"{recv_name or '?'}.get(timeout=...)"
        if not call.args and not call.keywords and recv_name \
                and _QUEUEISH.search(recv_name):
            return f"{recv_name}.get()"
        return None
    if attr == "wait":
        return f"{recv_name or '?'}.wait()"
    if attr == "send":
        return f"{recv_name or '?'}.send() (socket/pump I/O)"
    if attr in _BLOCKING_ATTRS:
        return f"{recv_name or '?'}.{attr}()"
    if attr == "Pump":
        return "native pump construction (TCP connects)"
    if attr in ("setup", "remove") and recv_name == "cni":
        return f"cni.{attr}() (netns/network I/O)"
    return None


@dataclasses.dataclass
class _CallSite:
    form: str  # "self" | "bare" | "attr"
    target: str
    line: int
    recv: "str | None" = None  # terminal receiver name (attr form)


@dataclasses.dataclass
class _LockBlock:
    name: str
    line: int
    module: str
    inner_locks: list  # (name, line, module)
    calls: list  # _CallSite
    blocking: list  # (reason, line)


class _FuncInfo:
    def __init__(self, mod: Module, cls: "str | None", node) -> None:
        self.mod = mod
        self.cls = cls
        self.name = node.name
        self.node = node
        self.qual = f"{mod.modname}.{cls + '.' if cls else ''}{node.name}"
        self.blocks: list[_LockBlock] = []   # with-lock blocks in this fn
        self.locks: list[tuple] = []         # (name, line) acquired anywhere
        self.calls: list[_CallSite] = []     # calls anywhere in fn
        self.blocking: list[tuple] = []      # (reason, line) anywhere
        # transitive closures (filled by _Index.solve)
        self.t_locks: dict = {}              # name -> chain str
        self.t_blocking: dict = {}           # reason -> chain str


def _classify_call(call: ast.Call) -> "_CallSite | None":
    fn = call.func
    if isinstance(fn, ast.Name):
        return _CallSite("bare", fn.id, call.lineno)
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            return _CallSite("self", fn.attr, call.lineno)
        return _CallSite("attr", fn.attr, call.lineno, _terminal(recv))
    return None


def _scan_function(fi: _FuncInfo) -> None:
    """Populate a _FuncInfo by walking its body with a with-lock stack.
    Nested function/class definitions are separate scopes and skipped."""
    mod = fi.mod

    def suppressed(line: int, rule: str) -> bool:
        s = mod.consume_suppression(line, rule)
        if s is not None:
            mod.scan_suppressed += 1
            return True
        return False

    def on_lock(name: str, line: int, stack: list) -> None:
        fi.locks.append((name, line))
        for blk in stack:
            blk.inner_locks.append((name, line, mod.modname))

    def on_call(call: ast.Call, stack: list) -> None:
        reason = blocking_reason(call)
        if reason is not None and not suppressed(
            call.lineno, "blocking-under-lock"
        ):
            fi.blocking.append((reason, call.lineno))
            for blk in stack:
                blk.blocking.append((reason, call.lineno))
        site = _classify_call(call)
        if site is not None:
            fi.calls.append(site)
            for blk in stack:
                blk.calls.append(site)

    def walk(node: ast.AST, stack: list) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            new_stack = list(stack)
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        on_call(sub, new_stack)
                name = _terminal(item.context_expr)
                if is_lock_name(name):
                    on_lock(name, node.lineno, new_stack)
                    blk = _LockBlock(
                        name, node.lineno, mod.modname, [], [], []
                    )
                    fi.blocks.append(blk)
                    new_stack = new_stack + [blk]
            for stmt in node.body:
                walk(stmt, new_stack)
            return
        if isinstance(node, ast.If):
            # the `if lock.acquire(blocking=False): ... finally release`
            # probe pattern (engine._PumpGroup): the if-body runs under
            # the lock
            test = node.test
            if (
                isinstance(test, ast.Call)
                and isinstance(test.func, ast.Attribute)
                and test.func.attr == "acquire"
                and is_lock_name(_terminal(test.func.value))
            ):
                name = _terminal(test.func.value)
                on_lock(name, node.lineno, stack)
                blk = _LockBlock(name, node.lineno, mod.modname, [], [], [])
                fi.blocks.append(blk)
                for stmt in node.body:
                    walk(stmt, stack + [blk])
                for stmt in node.orelse:
                    walk(stmt, stack)
                return
        if isinstance(node, ast.Call):
            on_call(node, stack)
            for child in ast.iter_child_nodes(node):
                walk(child, stack)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, stack)

    for stmt in fi.node.body:
        walk(stmt, [])


class _Index:
    """Package-wide function index + call resolution + transitive solve."""

    def __init__(self, mods: list[Module]) -> None:
        self.funcs: list[_FuncInfo] = []
        self.by_module: dict[str, dict[str, _FuncInfo]] = {}
        self.by_class: dict[str, dict[str, _FuncInfo]] = {}
        self.bases: dict[str, list[str]] = {}
        self.by_name: dict[str, list[_FuncInfo]] = {}
        self.rlocks: set[tuple] = set()  # (module, name)
        for mod in mods:
            self._index_module(mod)
        for fi in self.funcs:
            _scan_function(fi)
        self._solve()

    def _index_module(self, mod: Module) -> None:
        mod_funcs = self.by_module.setdefault(mod.modname, {})

        def add(fi: _FuncInfo) -> None:
            self.funcs.append(fi)
            self.by_name.setdefault(fi.name, []).append(fi)

        for node in mod.tree.body:
            if isinstance(node, ast.FunctionDef):
                fi = _FuncInfo(mod, None, node)
                mod_funcs[node.name] = fi
                add(fi)
            elif isinstance(node, ast.ClassDef):
                self.bases[node.name] = [
                    b.id for b in node.bases if isinstance(b, ast.Name)
                ] + [
                    b.attr for b in node.bases if isinstance(b, ast.Attribute)
                ]
                methods = self.by_class.setdefault(node.name, {})
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        fi = _FuncInfo(mod, node.name, sub)
                        methods[sub.name] = fi
                        add(fi)
        # RLock creations: with-reentry of these is legal
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _terminal(node.value.func) == "RLock"
            ):
                for tgt in node.targets:
                    name = _terminal(tgt)
                    if name:
                        self.rlocks.add((mod.modname, name))

    def is_rlock(self, name: str) -> bool:
        return any(n == name for _m, n in self.rlocks)

    def _resolve_in_class(self, cls: "str | None", target: str):
        seen = set()
        while cls is not None and cls not in seen:
            seen.add(cls)
            hit = self.by_class.get(cls, {}).get(target)
            if hit is not None:
                return hit
            parents = self.bases.get(cls, [])
            cls = parents[0] if parents else None
        return None

    def resolve(self, fi: _FuncInfo, site: _CallSite) -> list[_FuncInfo]:
        if site.form == "self":
            hit = self._resolve_in_class(fi.cls, site.target)
            if hit is not None:
                return [hit]
            # fall through to unique-global
        elif site.form == "attr" and site.recv in RECEIVER_CLASS_HINTS:
            hit = self._resolve_in_class(
                RECEIVER_CLASS_HINTS[site.recv], site.target
            )
            if hit is not None:
                return [hit]
        elif site.form == "bare":
            hit = self.by_module.get(fi.mod.modname, {}).get(site.target)
            return [hit] if hit is not None else []
        if site.target in _COMMON_NAMES:
            return []
        cands = self.by_name.get(site.target, [])
        return cands if len(cands) == 1 else []

    def _solve(self) -> None:
        """Fixpoint over the call graph: fold callees' locks and blocking
        calls into each caller, keeping one representative chain."""
        changed = True
        rounds = 0
        while changed and rounds < 20:
            changed = False
            rounds += 1
            for fi in self.funcs:
                want_locks = {name: "" for name, _ in fi.locks}
                want_blk = {r: "" for r, _ in fi.blocking}
                for site in fi.calls:
                    for callee in self.resolve(fi, site):
                        if callee is fi:
                            continue
                        step = callee.qual
                        for name, chain in list(callee.t_locks.items()):
                            want_locks.setdefault(
                                name, f"{step} -> {chain}" if chain else step
                            )
                        for r, chain in list(callee.t_blocking.items()):
                            want_blk.setdefault(
                                r, f"{step} -> {chain}" if chain else step
                            )
                if want_locks.keys() != fi.t_locks.keys():
                    fi.t_locks = want_locks
                    changed = True
                if want_blk.keys() != fi.t_blocking.keys():
                    fi.t_blocking = want_blk
                    changed = True


# One index serves both lock rules in a run: building it (scan + call-
# graph fixpoint) is the expensive half of the analysis.
_index_cache: "tuple[tuple, _Index] | None" = None


def build_index(mods: list[Module]) -> _Index:
    global _index_cache
    key = tuple(id(m) for m in mods)
    if _index_cache is not None and _index_cache[0] == key:
        return _index_cache[1]
    idx = _Index(mods)
    _index_cache = (key, idx)
    return idx


def _order_violation(index: _Index, held: str, held_mod: str,
                     inner: str, inner_mod: str) -> "str | None":
    lh, li = lock_level(held), lock_level(inner)
    if inner == held:
        if inner_mod == held_mod and index.is_rlock(inner):
            return None  # re-entrant acquisition of the same RLock
        return (
            f"re-acquires {inner} while already holding it "
            "(self-deadlock unless RLock)"
        )
    if li < lh:
        return (
            f"acquires {inner} (level {li}) while holding {held} "
            f"(level {lh}): out of declared lock order"
        )
    if li == lh:
        return (
            f"acquires {inner} (level {li}) while holding {held} "
            f"(level {lh}): same-level locks have no declared order"
        )
    return None


class LockOrderRule(Rule):
    name = "lock-order"
    description = (
        "nested lock acquisitions must follow the declared order "
        "stage_lock -> _alloc_lock -> _gen_lock -> leaves"
    )

    def check_project(self, mods, root):
        index = build_index(mods)
        seen = set()
        for fi in index.funcs:
            for blk in fi.blocks:
                # direct syntactic nesting: report at the INNER
                # acquisition, where the out-of-order take happens
                for name, line, imod in blk.inner_locks:
                    msg = _order_violation(
                        index, blk.name, blk.module, name, imod
                    )
                    if msg:
                        key = (fi.mod.rel, line, msg)
                        if key not in seen:
                            seen.add(key)
                            yield Finding(
                                fi.mod.rel, line, self.name,
                                f"in {fi.qual}: {msg}",
                            )
                # transitive via resolved calls
                for site in blk.calls:
                    for callee in index.resolve(fi, site):
                        for name, chain in callee.t_locks.items():
                            msg = _order_violation(
                                index, blk.name, blk.module,
                                name, callee.mod.modname,
                            )
                            if msg:
                                path = (
                                    f"{callee.qual} -> {chain}" if chain
                                    else callee.qual
                                )
                                msg2 = (
                                    f"in {fi.qual}: {msg} (via {path})"
                                )
                                key = (fi.mod.rel, blk.line, msg2)
                                if key not in seen:
                                    seen.add(key)
                                    yield Finding(
                                        fi.mod.rel, blk.line, self.name, msg2
                                    )


def _paired_cond_wait(reason: str, held: str) -> bool:
    """A ``<stem>_cond.wait()`` under ``<stem>_lock`` is the
    threading.Condition contract working as designed: wait() atomically
    RELEASES the lock that backs the condition while sleeping, so it is
    the one blocking shape that cannot convoy the lock it is charged
    against. The pairing is by naming convention and exact: the same
    wait under any OTHER lock (a shard lock, say) still convoys that
    lock and stays a finding."""
    suffix = "_cond.wait()"
    if not reason.endswith(suffix):
        return False
    return held == reason[: -len(suffix)] + "_lock"


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    description = (
        "no thread joins, queue/event waits, socket/pump I/O, apiserver "
        "round-trips, or CNI provider calls while holding a lock"
    )

    def check_project(self, mods, root):
        index = build_index(mods)
        seen = set()
        for fi in index.funcs:
            for blk in fi.blocks:
                for reason, line in blk.blocking:
                    if _paired_cond_wait(reason, blk.name):
                        continue
                    msg = (
                        f"in {fi.qual}: {reason} while holding {blk.name}"
                    )
                    key = (fi.mod.rel, line, msg)
                    if key not in seen:
                        seen.add(key)
                        yield Finding(fi.mod.rel, line, self.name, msg)
                for site in blk.calls:
                    for callee in index.resolve(fi, site):
                        for reason, chain in callee.t_blocking.items():
                            if _paired_cond_wait(reason, blk.name):
                                continue
                            path = (
                                f"{callee.qual} -> {chain}" if chain
                                else callee.qual
                            )
                            msg = (
                                f"in {fi.qual}: {reason} while holding "
                                f"{blk.name} (via {path})"
                            )
                            key = (fi.mod.rel, blk.line, msg)
                            if key not in seen:
                                seen.add(key)
                                yield Finding(
                                    fi.mod.rel, blk.line, self.name, msg
                                )


class UnusedLockRule(Rule):
    name = "unused-lock"
    description = "a threading.Lock/RLock created but acquired on no path"

    def check_project(self, mods, root):
        created: list[tuple] = []  # (mod, name, line)
        used: set[str] = set()
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    ctor = _terminal(node.value.func)
                    if ctor in ("Lock", "RLock", "allocate_lock"):
                        for tgt in node.targets:
                            name = _terminal(tgt)
                            if name:
                                created.append((mod, name, node.lineno))
                elif isinstance(node, ast.With):
                    for item in node.items:
                        name = _terminal(item.context_expr)
                        if is_lock_name(name):
                            used.add(name)
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    if node.func.attr in ("acquire", "release"):
                        name = _terminal(node.func.value)
                        if is_lock_name(name):
                            used.add(name)
                elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load
                ):
                    # aliased/shared elsewhere (`e._alloc_lock =
                    # parent._alloc_lock`, passing a lock to Condition):
                    # the alias site counts as a use of the name
                    if is_lock_name(node.attr):
                        used.add(node.attr)
        for mod, name, line in created:
            if name not in used:
                yield Finding(
                    mod.rel, line, self.name,
                    f"lock {name} is created but never acquired on any "
                    "path in the analyzed tree",
                )
