"""Runtime lock-order witness: instrumented Lock/RLock for tests.

The static rules prove what the source *says*; this proves what the
threads *do*. While installed, every lock created through
``threading.Lock``/``threading.RLock`` is wrapped: each acquisition
records which witnessed locks the thread already holds, building a
directed acquisition-order graph whose nodes are lock *creation sites*
(``module:varname``, inferred from the source line of the constructor
call). Two failure modes are detected the moment their edge appears,
each reported with BOTH acquisition stacks:

- **order-graph cycle** — lock A taken while holding B on one thread and
  B taken while holding A on another is a deadlock waiting for the right
  interleaving, even if the soak run never hit it;
- **declared-order violation** — an edge that contradicts the table in
  ``analysis/locks.py`` (stage_lock -> _alloc_lock -> _gen_lock ->
  leaves), checked only for locks the table names, so stdlib internals
  (queue mutexes, futures) never false-positive.

Enabled for the lane suite via the ``KWOK_TPU_LOCK_WITNESS=1`` conftest
fixture (``make lane-check``); usable directly as::

    with witness() as w:
        ...exercise engine...
    # fixture calls w.assert_clean() -> AssertionError with both stacks

Only locks created *while installed* are witnessed, so module-import
locks (logging handlers, jax internals) stay out of the graph.
"""

from __future__ import annotations

import linecache
import re
import sys
import threading
import traceback

from kwok_tpu.analysis.locks import LOCK_ORDER

_NAME_RE = re.compile(r"([A-Za-z_]\w*)\s*=\s*(?:threading\s*\.\s*)?R?Lock\(")


def _creation_site() -> tuple:
    """(module_basename, varname|None, file:line) of the frame that called
    the patched constructor."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return ("?", None, "?")
    fname = f.f_code.co_filename
    lineno = f.f_lineno
    mod = fname.rsplit("/", 1)[-1].rsplit(".", 1)[0]
    line = linecache.getline(fname, lineno)
    m = _NAME_RE.search(line)
    return (mod, m.group(1) if m else None, f"{fname}:{lineno}")


def _stack(skip: int = 2):
    return traceback.StackSummary.extract(
        traceback.walk_stack(sys._getframe(skip)), limit=14,
        lookup_lines=False,
    )


class Violation:
    def __init__(self, kind: str, message: str, stacks: list) -> None:
        self.kind = kind
        self.message = message
        self.stacks = stacks  # [(title, StackSummary), ...]

    def format(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for title, stack in self.stacks:
            out.append(f"--- {title} ---")
            out.extend(s.rstrip() for s in stack.format())
        return "\n".join(out)


class _Held(threading.local):
    def __init__(self):
        self.stack = []  # [(wrapper, node_key, StackSummary), ...]


class LockWitness:
    """Acquisition-edge recorder + cycle/declared-order checker."""

    _installed: "LockWitness | None" = None

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()  # guards edges/violations
        self._held = _Held()
        # (a_key, b_key) -> (thread, stack_of_a, stack_of_b)
        self.edges: dict = {}
        self.succ: dict = {}  # a_key -> set of b_keys
        self.violations: list[Violation] = []

    # ------------------------------------------------------------ recording

    def note_acquired(self, wrapper: "_WitnessLockBase") -> None:
        held = self._held.stack
        if any(w is wrapper for w, _k, _s in held):
            # re-entrant acquisition of the same instance (RLock, or a
            # Condition re-acquire): not an ordering edge
            held.append((wrapper, wrapper.key, None))
            return
        stack = _stack(3)
        for _w, held_key, held_stack in list(held):
            if held_stack is None:
                continue  # re-entrant duplicate entry
            self._add_edge(held_key, wrapper.key, held_stack, stack)
        held.append((wrapper, wrapper.key, stack))

    def note_released(self, wrapper: "_WitnessLockBase") -> None:
        held = self._held.stack
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is wrapper:
                del held[i]
                return

    def drop_all(self, wrapper: "_WitnessLockBase") -> int:
        """Condition._release_save: drop every recursion level; returns
        how many were held so _acquire_restore can re-book them."""
        held = self._held.stack
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is wrapper:
                del held[i]
                n += 1
        return n

    # ------------------------------------------------------------- checking

    def _add_edge(self, a: tuple, b: tuple, stack_a, stack_b) -> None:
        with self._graph_lock:
            if (a, b) in self.edges:
                return
            self.edges[(a, b)] = (
                threading.current_thread().name, stack_a, stack_b
            )
            if a == b:
                # two DISTINCT instances sharing one creation site (per-
                # lane stage_locks, pump group locks) nested: instances
                # of one lock class have no defined order, so the
                # opposite interleaving on another thread is an ABBA
                # deadlock. Report it as its own diagnostic — a self-edge
                # must never enter the cycle graph, where every later
                # path through the node would read as a spurious cycle.
                self.violations.append(Violation(
                    "same-site-nesting",
                    f"two distinct locks created at {self._node_str(a)} "
                    "nested on thread "
                    f"{threading.current_thread().name}: instances of one "
                    "lock class have no defined order (ABBA hazard)",
                    [
                        (f"holding first {self._node_str(a)}, acquired at",
                         stack_a),
                        (f"acquiring second {self._node_str(b)} at",
                         stack_b),
                    ],
                ))
                return
            self.succ.setdefault(a, set()).add(b)
            self._check_declared(a, b, stack_a, stack_b)
            self._check_cycle(a, b, stack_a, stack_b)

    @staticmethod
    def _node_str(key: tuple) -> str:
        mod, name, site = key
        return f"{mod}.{name or '<anon>'} ({site})"

    def _check_declared(self, a: tuple, b: tuple, stack_a, stack_b) -> None:
        name_a, name_b = a[1], b[1]
        if name_a not in LOCK_ORDER or name_b not in LOCK_ORDER:
            return
        la, lb = LOCK_ORDER[name_a], LOCK_ORDER[name_b]
        if lb < la or (lb == la and a != b):
            self.violations.append(Violation(
                "declared-order",
                f"{self._node_str(b)} (level {lb}) acquired while holding "
                f"{self._node_str(a)} (level {la}) on thread "
                f"{threading.current_thread().name}",
                [
                    (f"holding {self._node_str(a)}, acquired at", stack_a),
                    (f"acquiring {self._node_str(b)} at", stack_b),
                ],
            ))

    def _check_cycle(self, a: tuple, b: tuple, stack_a, stack_b) -> None:
        """The new edge a->b closes a cycle iff a is reachable from b."""
        seen = set()
        frontier = [b]
        path = {b: None}
        while frontier:
            n = frontier.pop()
            if n == a:
                # rebuild the b..a path for the message
                hops = []
                cur = a
                while cur is not None:
                    hops.append(cur)
                    cur = path.get(cur)
                cycle = " -> ".join(
                    self._node_str(k) for k in reversed(hops)
                ) + f" -> {self._node_str(b)}"
                stacks = [
                    (f"edge {self._node_str(a)} -> {self._node_str(b)}: "
                     "holder stack", stack_a),
                    ("acquirer stack", stack_b),
                ]
                rev = self.edges.get((b, a))
                if rev is not None:
                    thread, sa, sb = rev
                    stacks.append((
                        f"opposite edge {self._node_str(b)} -> "
                        f"{self._node_str(a)} (thread {thread}): "
                        "holder stack", sa,
                    ))
                    stacks.append(("opposite acquirer stack", sb))
                self.violations.append(Violation(
                    "order-cycle",
                    "lock acquisition graph has a cycle: " + cycle,
                    stacks,
                ))
                return
            if n in seen:
                continue
            seen.add(n)
            for m in self.succ.get(n, ()):
                if m not in path:
                    path[m] = n
                frontier.append(m)

    def assert_clean(self) -> None:
        if self.violations:
            raise AssertionError(
                "lock-order witness recorded "
                f"{len(self.violations)} violation(s):\n\n"
                + "\n\n".join(v.format() for v in self.violations)
            )

    # ---------------------------------------------------------- installation

    @classmethod
    def install(cls) -> "LockWitness":
        if cls._installed is not None:
            return cls._installed
        w = cls()
        cls._installed = w
        cls._orig_lock = threading.Lock
        cls._orig_rlock = threading.RLock

        def make_lock():
            return _WitnessLock(cls._orig_lock(), w, _creation_site())

        def make_rlock():
            return _WitnessRLock(cls._orig_rlock(), w, _creation_site())

        threading.Lock = make_lock
        threading.RLock = make_rlock
        return w

    @classmethod
    def uninstall(cls) -> None:
        if cls._installed is None:
            return
        threading.Lock = cls._orig_lock
        threading.RLock = cls._orig_rlock
        cls._installed = None


class _WitnessLockBase:
    def __init__(self, inner, witness: LockWitness, site: tuple) -> None:
        self._inner = inner
        self._witness = witness
        self.key = site  # (module, varname, file:line)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness.note_acquired(self)
        return ok

    def release(self) -> None:
        self._witness.note_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        # anything we don't instrument delegates to the real lock
        # (_at_fork_reinit, acquire_lock aliases, ...): stdlib modules
        # touch these at import time (concurrent.futures registers
        # _at_fork_reinit with os.register_at_fork)
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<witnessed {self._inner!r} as {self.key}>"


class _WitnessLock(_WitnessLockBase):
    pass


class _WitnessRLock(_WitnessLockBase):
    # threading.Condition protocol for RLocks
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        n = self._witness.drop_all(self)
        return (self._inner._release_save(), n)

    def _acquire_restore(self, state):
        inner_state, n = state
        self._inner._acquire_restore(inner_state)
        for _ in range(max(1, n)):
            self._witness._held.stack.append((self, self.key, None))


def witness():
    """Context manager installing a witness (test helper). Joining an
    already-installed witness (the conftest fixture's) is allowed; only
    the installer uninstalls on exit."""

    class _Ctx:
        def __enter__(self):
            self._owner = LockWitness._installed is None
            self.w = LockWitness.install()
            return self.w

        def __exit__(self, *exc):
            if self._owner:
                LockWitness.uninstall()

    return _Ctx()
