"""kwoklint framework: modules, findings, suppressions, the rule API.

Small on purpose. A rule sees parsed modules (``ast`` trees + raw source)
and yields :class:`Finding`s; the framework owns everything else — file
discovery, suppression comments, severity ordering, text/JSON rendering,
exit codes. Rules never import heavyweight runtime deps (no jax, no
engine), so ``make analyze`` runs in seconds and can gate CI.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import time
import tokenize
from typing import Iterable, Iterator

SEVERITIES = ("error", "warning")

# Inline suppression: `# kwoklint: disable=rule-a,rule-b -- why this is ok`
# on the offending line or the line directly above it. The trailing text is
# the justification and is MANDATORY (acceptance criterion: every
# suppression carries one); a bare suppression is reported itself.
_SUPPRESS_RE = re.compile(
    r"#\s*kwoklint:\s*disable=([A-Za-z0-9_,\-]+)\s*(.*)$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: a file:line, the rule that fired, and the story."""

    path: str  # repo-relative path
    line: int
    rule: str
    message: str
    severity: str = "error"

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.severity} [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    line: int
    rules: tuple[str, ...]
    justification: str


class Module:
    """One parsed source file plus its suppression table."""

    def __init__(self, path: str, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.suppressions: dict[int, Suppression] = {}
        # suppression lines that silenced something this run (finding- or
        # scan-level); anything left over is stale and reported as such
        self.used_suppressions: set[int] = set()
        self.scan_suppressed = 0  # would-be findings silenced at scan time
        self._scan_suppressions()

    @property
    def modname(self) -> str:
        return os.path.basename(self.path).rsplit(".", 1)[0]

    def _scan_suppressions(self) -> None:
        # tokenize, not line-regex: a '#' inside a string literal must not
        # read as a comment (the rules' own sources mention the marker)
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline
            )
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                just = m.group(2).strip().lstrip("-—:· ").strip()
                self.suppressions[tok.start[0]] = Suppression(
                    tok.start[0], rules, just
                )
        except tokenize.TokenError:
            # a half-written file still gets analyzed from its (already
            # parsed) AST; only the comment scan degrades
            pass

    def suppression_for(self, line: int, rule: str) -> Suppression | None:
        """A finding at `line` is suppressed by a marker on that line or on
        the directly preceding (comment-only) line."""
        for ln in (line, line - 1):
            s = self.suppressions.get(ln)
            if s is not None and (rule in s.rules or "all" in s.rules):
                return s
        return None

    def consume_suppression(self, line: int, rule: str) -> Suppression | None:
        """suppression_for + usage marking: consumed suppressions are
        live; any suppression never consumed by the full rule pack is
        stale and surfaces as an `unused-suppression` finding."""
        s = self.suppression_for(line, rule)
        if s is not None:
            self.used_suppressions.add(s.line)
        return s


class Rule:
    """Base rule. Subclasses set ``name``/``description`` and implement
    ``check_module`` (per file) or ``check_project`` (cross-file)."""

    name = "abstract"
    description = ""

    def check_module(self, mod: Module) -> Iterable[Finding]:
        return ()

    def check_project(self, mods: list[Module], root: str) -> Iterable[Finding]:
        for mod in mods:
            yield from self.check_module(mod)


def iter_py_files(paths: list[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in sorted(dirnames) if d != "__pycache__"
                ]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def load_module(path: str, root: str) -> Module:
    rel = os.path.relpath(path, root)
    with open(path, encoding="utf-8") as fh:
        return Module(path, rel, fh.read())


class Analyzer:
    """Load modules, run rules, apply suppressions, report."""

    def __init__(self, root: str, rules: "list[Rule] | None" = None) -> None:
        self.root = root
        self.rules = rules if rules is not None else all_rules(root)

    def load(self, paths: list[str]) -> list[Module]:
        mods = []
        for path in iter_py_files(paths):
            try:
                mods.append(load_module(path, self.root))
            except SyntaxError as e:
                mods_rel = os.path.relpath(path, self.root)
                raise SystemExit(f"kwoklint: cannot parse {mods_rel}: {e}")
        return mods

    def run(self, paths: list[str]) -> tuple[list[Finding], int]:
        """Returns (unsuppressed findings, suppressed count). Suppressions
        without a justification surface as `bare-suppression` findings."""
        mods = self.load(paths)
        by_rel = {m.rel: m for m in mods}
        findings: list[Finding] = []
        suppressed = 0
        self.timings: dict[str, float] = {}
        for rule in self.rules:
            t0 = time.perf_counter()
            for f in rule.check_project(mods, self.root):
                mod = by_rel.get(f.path)
                s = mod.consume_suppression(f.line, f.rule) if mod else None
                if s is not None:
                    suppressed += 1
                else:
                    findings.append(f)
            self.timings[rule.name] = time.perf_counter() - t0
        # a suppression may also silence a would-be finding at scan time
        # (blocking-under-lock markers stop transitive propagation at the
        # source); rules count those on the module as they scan
        suppressed += sum(m.scan_suppressed for m in mods)
        # every suppression must justify itself AND stay live: staleness
        # is only judged when every rule the marker names actually ran
        # (a --rule subset must not flag markers for the rules it skipped)
        active = {r.name for r in self.rules}
        active |= {"bare-suppression", "unused-suppression"}
        for mod in mods:
            for s in mod.suppressions.values():
                if not s.justification:
                    findings.append(Finding(
                        mod.rel, s.line, "bare-suppression",
                        "suppression without a justification comment "
                        "(write `# kwoklint: disable=<rule> -- <why>`)",
                    ))
                elif (
                    s.line not in mod.used_suppressions
                    and set(s.rules) <= active
                ):
                    findings.append(Finding(
                        mod.rel, s.line, "unused-suppression",
                        "suppression matched no finding — stale: remove "
                        "it, or fix the rule list "
                        f"({', '.join(s.rules)})",
                    ))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings, suppressed


def all_rules(root: str) -> list[Rule]:
    """The shipped rule pack. Imported lazily so `core` stays dependency-
    free for the witness (which loads in test processes)."""
    from kwok_tpu.analysis.cclint import (
        CcFenceFirstRule,
        CcLockOrderRule,
        CcSocketUnderLockRule,
    )
    from kwok_tpu.analysis.hygiene import SilentExceptRule
    from kwok_tpu.analysis.locks import (
        BlockingUnderLockRule,
        LockOrderRule,
        UnusedLockRule,
    )
    from kwok_tpu.analysis.metrics_doc import MetricsContractRule
    from kwok_tpu.analysis.purity import KernelPurityRule
    from kwok_tpu.analysis.races import SharedStateRule
    from kwok_tpu.analysis.shmproto import ShmProtocolRule
    from kwok_tpu.analysis.spawnonly import SpawnOnlyRule

    return [
        LockOrderRule(),
        BlockingUnderLockRule(),
        UnusedLockRule(),
        SharedStateRule(),
        ShmProtocolRule(),
        KernelPurityRule(),
        SilentExceptRule(),
        SpawnOnlyRule(),
        MetricsContractRule(doc_path=os.path.join(root, "docs", "observability.md")),
        CcLockOrderRule(),
        CcFenceFirstRule(),
        CcSocketUnderLockRule(),
    ]
