// kwok_tpu native HTTP pump: batched pipelined unary requests.
//
// The engine's patch egress and the soak rig's load generation are
// request-per-object HTTP (the Kubernetes API has no batch verb), so at
// O(10k) objects/s the per-request client cost dominates a Python sender —
// especially on small hosts where engine, loader and apiserver share
// cores. This pump issues a whole batch of prepared (method, path, body)
// requests over a small pool of persistent connections, pipelining within
// each connection (write side streams all requests in large buffers; read
// side consumes responses in order), entirely outside the GIL.
//
// Protocol assumptions (valid for kube-apiservers and the mock): HTTP/1.1
// keep-alive, responses carry Content-Length or chunked bodies, response
// bodies are discarded (the engine learns outcomes from the watch echo;
// only status codes are reported back).
//
// Failure contract: if a connection dies mid-batch, every unsent/unread
// request on it gets status 0 and the connection is re-established on the
// next call; the Python caller decides whether to retry.
//
// Build: part of libkwokcodec.so (see native/__init__.py _build).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Conn {
  int fd = -1;
};

struct Pump {
  std::string host;
  int port = 0;
  std::vector<Conn> conns;
  std::string header_extra;  // e.g. "Authorization: Bearer ...\r\n"
  // send-path attribution (ISSUE 11): cumulative wall ns split between
  // the request-writing side and the response-reading side, summed
  // across connections (they overlap, so write+read can exceed batch).
  // Two clock reads per connection per BATCH — amortized over hundreds
  // of requests, so the stats are always on.
  std::atomic<uint64_t> batches{0};
  std::atomic<uint64_t> requests{0};
  std::atomic<uint64_t> batch_ns{0};
  std::atomic<uint64_t> write_ns{0};
  std::atomic<uint64_t> read_ns{0};
};

uint64_t pump_now_ns() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::mutex g_pumps_mu;
std::map<int64_t, Pump*> g_pumps;
int64_t g_next_id = 1;

int dial(const std::string& host, int port) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  char portbuf[16];
  snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host.c_str(), portbuf, &hints, &res) != 0) return -1;
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    // a stalled (not dead) server must fail the batch, not wedge the
    // engine's egress forever — the Python client this replaces had a
    // per-request timeout; timed-out requests report status 0
    struct timeval tv{60, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  }
  return fd;
}

bool send_all(int fd, const char* data, size_t n) {
  while (n > 0) {
    ssize_t w = send(fd, data, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    data += w;
    n -= (size_t)w;
  }
  return true;
}

struct Slices {
  const char* blob;
  const int64_t* off;
  const char* ptr(int64_t i) const { return blob + off[i]; }
  int64_t len(int64_t i) const { return off[i + 1] - off[i]; }
};

// Streaming response reader over a buffered connection.
struct RespReader {
  int fd;
  std::string buf;
  size_t pos = 0;

  bool fill() {
    char tmp[65536];
    ssize_t n = recv(fd, tmp, sizeof tmp, 0);
    if (n <= 0) return false;
    if (pos > (1u << 20) && pos * 2 > buf.size()) {
      buf.erase(0, pos);
      pos = 0;
    }
    buf.append(tmp, n);
    return true;
  }

  // reads until the delimiter appears at/after pos; returns index or npos
  size_t find(const char* delim) {
    size_t at;
    while ((at = buf.find(delim, pos)) == std::string::npos) {
      if (!fill()) return std::string::npos;
    }
    return at;
  }

  bool need(size_t n) {
    while (buf.size() - pos < n) {
      if (!fill()) return false;
    }
    return true;
  }

  // Parses one response; returns status code or 0 on connection error.
  int read_response() {
    size_t hdr_end = find("\r\n\r\n");
    if (hdr_end == std::string::npos) return 0;
    std::string head = buf.substr(pos, hdr_end - pos);
    pos = hdr_end + 4;
    int code = 0;
    size_t sp = head.find(' ');
    if (sp != std::string::npos) code = atoi(head.c_str() + sp + 1);
    // locate framing headers (case-insensitive)
    long content_len = -1;
    bool chunked = false;
    size_t lpos = 0;
    while (lpos < head.size()) {
      size_t e = head.find("\r\n", lpos);
      if (e == std::string::npos) e = head.size();
      std::string line = head.substr(lpos, e - lpos);
      lpos = e + 2;
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string k = line.substr(0, colon);
      for (auto& c : k) c = (char)tolower((unsigned char)c);
      std::string v = line.substr(colon + 1);
      size_t a = v.find_first_not_of(" \t");
      if (a != std::string::npos) v = v.substr(a);
      if (k == "content-length") content_len = atol(v.c_str());
      else if (k == "transfer-encoding" && v.rfind("chunked", 0) == 0)
        chunked = true;
    }
    if (chunked) {
      while (true) {
        size_t le = find("\r\n");
        if (le == std::string::npos) return 0;
        long sz = strtol(buf.c_str() + pos, nullptr, 16);
        pos = le + 2;
        if (!need((size_t)sz + 2)) return 0;
        pos += (size_t)sz + 2;
        if (sz == 0) break;
      }
    } else if (content_len > 0) {
      if (!need((size_t)content_len)) return 0;
      pos += (size_t)content_len;
    }
    return code;
  }
};

// Appends the COMPLETE wire frame (request line + headers + body) of
// request i to `out` — the one pluggable piece of run_conn, so the
// classic 4-slice batch and the fused template-emit batch (codec.cc
// kwok_emit_pods -> kwok_pump_send2) share every byte of the
// connection/pipelining/failure machinery.
using FrameFn = std::function<void(std::string&, int32_t)>;

void run_conn(Pump* p, size_t ci, const FrameFn& frame,
              const std::vector<int32_t>& idxs, int32_t* status_out) {
  Conn& c = p->conns[ci];
  if (c.fd < 0) c.fd = dial(p->host, p->port);
  if (c.fd < 0) {
    for (int32_t i : idxs) status_out[i] = 0;
    return;
  }

  // writer thread streams all requests; this thread reads responses
  bool write_ok = true;
  std::thread writer([&] {
    uint64_t w0 = pump_now_ns();
    [&] {
      std::string out;
      out.reserve(1 << 20);
      for (int32_t i : idxs) {
        frame(out, i);
        if (out.size() >= (1 << 20)) {
          if (!send_all(c.fd, out.data(), out.size())) {
            write_ok = false;
            return;
          }
          out.clear();
        }
      }
      if (!out.empty() && !send_all(c.fd, out.data(), out.size()))
        write_ok = false;
    }();
    p->write_ns.fetch_add(pump_now_ns() - w0, std::memory_order_relaxed);
  });

  uint64_t r0 = pump_now_ns();
  RespReader rr{c.fd};
  size_t done = 0;
  for (; done < idxs.size(); done++) {
    int code = rr.read_response();
    if (code == 0) break;
    status_out[idxs[done]] = code;
  }
  p->read_ns.fetch_add(pump_now_ns() - r0, std::memory_order_relaxed);
  writer.join();
  if (done < idxs.size() || !write_ok) {
    for (size_t i = done; i < idxs.size(); i++) status_out[idxs[i]] = 0;
    close(c.fd);
    c.fd = -1;
  }
}

// ONE copy of the handle-lookup contract (nullptr = unknown handle, the
// callers' -1): every entry point resolves its Pump* here, exactly once.
Pump* lookup_pump(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_pumps_mu);
  auto it = g_pumps.find(handle);
  return it == g_pumps.end() ? nullptr : it->second;
}

// Shared batch body of kwok_pump_send / kwok_pump_send2: shard indices
// round-robin across the pool, run the connection threads, account
// stats, count 2xx. `p` is the caller's already-resolved pump.
int64_t pump_send_batch(Pump* p, int32_t n, const FrameFn& frame,
                        int32_t* status_out) {
  uint64_t b0 = pump_now_ns();

  size_t nconn = p->conns.size();
  std::vector<std::vector<int32_t>> shards(nconn);
  for (int32_t i = 0; i < n; i++) shards[i % nconn].push_back(i);

  std::vector<std::thread> threads;
  for (size_t ci = 0; ci < nconn; ci++) {
    if (shards[ci].empty()) continue;
    threads.emplace_back(run_conn, p, ci, std::cref(frame),
                         std::cref(shards[ci]), status_out);
  }
  for (auto& t : threads) t.join();
  p->batches.fetch_add(1, std::memory_order_relaxed);
  p->requests.fetch_add((uint64_t)n, std::memory_order_relaxed);
  p->batch_ns.fetch_add(pump_now_ns() - b0, std::memory_order_relaxed);

  int64_t ok = 0;
  for (int32_t i = 0; i < n; i++)
    if (status_out[i] >= 200 && status_out[i] < 300) ok++;
  return ok;
}

// One full request frame; the path is spliced from up to three pieces
// (prefix + per-request path + suffix — send2's "{base}{path}{suffix}").
void append_frame(std::string& out, const std::string& host,
                  const std::string& extra, const char* method,
                  int64_t method_len, const char* path0, int64_t path0_len,
                  const char* path, int64_t path_len,
                  const char* path2, int64_t path2_len, const char* ctype,
                  int64_t ctype_len, const char* body, int64_t body_len) {
  char clen[64];
  out.append(method, method_len);
  out += ' ';
  if (path0_len) out.append(path0, path0_len);
  out.append(path, path_len);
  if (path2_len) out.append(path2, path2_len);
  out += " HTTP/1.1\r\nHost: ";
  out += host;
  out += "\r\nContent-Type: ";
  if (ctype_len > 0) out.append(ctype, ctype_len);
  else out += "application/json";
  out += "\r\n";
  out += extra;
  int n = snprintf(clen, sizeof clen, "Content-Length: %lld\r\n\r\n",
                   (long long)body_len);
  out.append(clen, n);
  out.append(body, body_len);
}

}  // namespace

extern "C" {

int64_t kwok_pump_open(const char* host, int32_t port, int32_t nconn,
                       const char* header_extra) {
  Pump* p = new Pump;
  p->host = host;
  p->port = port;
  p->conns.resize(nconn > 0 ? nconn : 1);
  if (header_extra && header_extra[0]) p->header_extra = header_extra;
  std::lock_guard<std::mutex> lk(g_pumps_mu);
  int64_t id = g_next_id++;
  g_pumps[id] = p;
  return id;
}

// Issues n requests split round-robin across the pool; blocks until every
// response is read (or its connection died). status_out[i] = HTTP code, or
// 0 for connection failure. Returns the count of codes in [200, 300).
int64_t kwok_pump_send(int64_t handle, int32_t n,
                       const char* method_blob, const int64_t* method_off,
                       const char* path_blob, const int64_t* path_off,
                       const char* ctype_blob, const int64_t* ctype_off,
                       const char* body_blob, const int64_t* body_off,
                       int32_t* status_out) {
  Pump* p = lookup_pump(handle);
  if (!p) return -1;
  Slices method{method_blob, method_off};
  Slices path{path_blob, path_off};
  Slices ctype{ctype_blob, ctype_off};
  Slices body{body_blob, body_off};
  FrameFn frame = [&](std::string& out, int32_t i) {
    append_frame(out, p->host, p->header_extra, method.ptr(i),
                 method.len(i), nullptr, 0, path.ptr(i), path.len(i),
                 nullptr, 0, ctype.ptr(i), ctype.len(i), body.ptr(i),
                 body.len(i));
  };
  return pump_send_batch(p, n, frame, status_out);
}

// Single-method batch over a shared path prefix/suffix and ONE content
// type: "{method} {base}{path[i]}{suffix}" with body[i] — the wire shape
// of the engine's emit batches (every request is a status PATCH), built
// without per-request method/ctype marshalling. Called by codec.cc's
// fused kwok_emit_pods; also exported for direct use.
int64_t kwok_pump_send2(int64_t handle, int32_t n, const char* method,
                        const char* base, int64_t base_len,
                        const char* path_blob, const int64_t* path_off,
                        const char* suffix, int64_t suffix_len,
                        const char* ctype, int64_t ctype_len,
                        const char* body_blob, const int64_t* body_off,
                        int32_t* status_out) {
  Pump* p = lookup_pump(handle);
  if (!p) return -1;
  Slices path{path_blob, path_off};
  Slices body{body_blob, body_off};
  int64_t method_len = (int64_t)strlen(method);
  FrameFn frame = [&](std::string& out, int32_t i) {
    append_frame(out, p->host, p->header_extra, method, method_len, base,
                 base_len, path.ptr(i), path.len(i), suffix, suffix_len,
                 ctype, ctype_len, body.ptr(i), body.len(i));
  };
  return pump_send_batch(p, n, frame, status_out);
}

// Send-path attribution snapshot: out[5] = {batches, requests, batch_s,
// write_s, read_s}. write/read are summed across the pool's overlapping
// per-connection threads, so each can exceed batch_s on multi-conn pumps.
void kwok_pump_stats(int64_t handle, double* out) {
  Pump* p = lookup_pump(handle);
  if (!p) {
    for (int i = 0; i < 5; i++) out[i] = 0;
    return;
  }
  out[0] = (double)p->batches.load(std::memory_order_relaxed);
  out[1] = (double)p->requests.load(std::memory_order_relaxed);
  out[2] = (double)p->batch_ns.load(std::memory_order_relaxed) / 1e9;
  out[3] = (double)p->write_ns.load(std::memory_order_relaxed) / 1e9;
  out[4] = (double)p->read_ns.load(std::memory_order_relaxed) / 1e9;
}

void kwok_pump_close(int64_t handle) {
  Pump* p = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_pumps_mu);
    auto it = g_pumps.find(handle);
    if (it != g_pumps.end()) {
      p = it->second;
      g_pumps.erase(it);
    }
  }
  if (!p) return;
  for (Conn& c : p->conns)
    if (c.fd >= 0) close(c.fd);
  delete p;
}

}  // extern "C"
