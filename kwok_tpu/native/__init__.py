"""Native codec loader: compile-on-demand C++ with a pure-Python fallback.

The reference ships native static binaries for everything (SURVEY.md §2.4);
our compute path is JAX/XLA and the remaining native-worthy hot spot is the
host JSON egress. codec.cc is built here with g++ on first use (cached next
to the source, keyed by source mtime) and bound via ctypes. If no compiler
is available the engine silently falls back to kwok_tpu.edge.render — the
codec is a throughput optimization, never a functional dependency.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("kwok_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.cc")
_PUMP_SRC = os.path.join(_DIR, "pump.cc")
_INGEST_SRC = os.path.join(_DIR, "ingest.cc")
_LIB = os.path.join(_DIR, "libkwokcodec.so")
_APISERVER_SRC = os.path.join(_DIR, "apiserver.cc")
_APISERVER_BIN = os.path.join(_DIR, "kwok-mock-apiserver")
ABI_VERSION = 9

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
_apiserver_lock = threading.Lock()
_apiserver_path: str | None = None
_apiserver_tried = False


def _build() -> bool:
    cxx = os.environ.get("CXX", "g++")
    cmd = [
        cxx, "-O2", "-std=c++17", "-pthread", "-shared", "-fPIC",
        "-o", _LIB + ".tmp", _SRC, _PUMP_SRC, _INGEST_SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        logger.info("native codec build failed (%s); using python renderers", e)
        return False
    os.replace(_LIB + ".tmp", _LIB)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.kwok_codec_abi_version.restype = ctypes.c_int32
    lib.kwok_render_heartbeats.restype = ctypes.c_int64
    lib.kwok_render_heartbeats.argtypes = [
        ctypes.c_int32, u32p, ctypes.c_int32,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, ctypes.c_int32,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, ctypes.c_int64, i64p,
    ]
    lib.kwok_render_pod_statuses.restype = ctypes.c_int64
    lib.kwok_render_pod_statuses.argtypes = [
        ctypes.c_int32, u8p, u32p,
        ctypes.c_char_p, i64p,
        ctypes.c_int32, ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, ctypes.c_int64, i64p,
    ]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.kwok_pump_open.restype = ctypes.c_int64
    lib.kwok_pump_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
    ]
    lib.kwok_pump_send.restype = ctypes.c_int64
    lib.kwok_pump_send.argtypes = [
        ctypes.c_int64, ctypes.c_int32,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        i32p,
    ]
    lib.kwok_pump_close.restype = None
    lib.kwok_pump_close.argtypes = [ctypes.c_int64]
    lib.kwok_pump_stats.restype = None
    lib.kwok_pump_stats.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
    ]
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.kwok_parse_events.restype = ctypes.c_int64
    lib.kwok_parse_events.argtypes = [
        ctypes.c_char_p, i64p, ctypes.c_int32,
        u64p, u64p, u64p, u64p, u8p, i64p,
        ctypes.c_char_p, ctypes.c_int64, i64p,
        # ABI 7 pre-partitioned routing: kind_is_pods, n_shards,
        # shard_out, lane_idx, lane_off, route_info (null when n_shards=0)
        ctypes.c_int32, ctypes.c_int32, i32p, i32p, i64p, i64p,
    ]
    lib.kwok_fingerprint_statuses.restype = None
    lib.kwok_fingerprint_statuses.argtypes = [
        ctypes.c_char_p, i64p, ctypes.c_int32, u64p,
    ]
    lib.kwok_watch_open.restype = ctypes.c_void_p
    lib.kwok_watch_open.argtypes = [
        ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.kwok_watch_read.restype = ctypes.c_int64
    lib.kwok_watch_read.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
        i64p, ctypes.c_int64, i32p, i64p,
    ]
    lib.kwok_watch_close.restype = None
    lib.kwok_watch_close.argtypes = [ctypes.c_void_p]
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.kwok_emit_pods.restype = ctypes.c_int64
    lib.kwok_emit_pods.argtypes = [
        ctypes.c_int64, ctypes.c_int32,
        i32p, u32p,
        # template table: lit_blob, seg_code, seg_a, seg_b, tpl_off,
        # tpl_kind, tpl_ready
        ctypes.c_char_p, i32p, i64p, i64p, i64p, u8p, u8p,
        # columns: host, pod, start, ctrs, ictrs
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, ctypes.c_int32,  # now
        ctypes.c_char_p, ctypes.c_int64, i64p,  # out slab
        u64p,  # fingerprints
        # send half: base, paths, suffix, ctype, status
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64,
        i32p,
    ]
    lib.kwok_pump_send2.restype = ctypes.c_int64
    lib.kwok_pump_send2.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, i64p,
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, i64p,
        i32p,
    ]
    return lib


def load() -> ctypes.CDLL | None:
    """The codec library, building it if stale/missing; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        fresh = os.path.exists(_LIB) and os.path.getmtime(_LIB) >= max(
            os.path.getmtime(_SRC),
            os.path.getmtime(_PUMP_SRC),
            os.path.getmtime(_INGEST_SRC),
        )
        if not fresh and not _build():
            return None
        try:
            lib = _bind(ctypes.CDLL(_LIB))
            if lib.kwok_codec_abi_version() != ABI_VERSION:
                logger.info("native codec ABI mismatch; rebuilding")
                os.remove(_LIB)
                if not _build():
                    return None
                lib = _bind(ctypes.CDLL(_LIB))
        except OSError as e:
            logger.info("native codec load failed (%s)", e)
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


#: field order of EventRecord string fields (ingest.cc kwok_parse_events)
_REC_STRINGS = 11  # type, ns, name, nodeName, phase, podIP, hostIP,
#                    creation, containers, initContainers, trueConditions

# flags bits (ingest.cc)
REC_OK = 1
REC_DELETION = 2
REC_FINALIZERS = 4
REC_READINESS_GATES = 8
REC_STATUS_SCALAR_ONLY = 16
# bits 5-6: event type code (ABI 7) — lets batch consumers classify
# without touching the type string
REC_TYPE_MASK = 0x60
REC_TYPE_ADDED = 0x20
REC_TYPE_MODIFIED = 0x40
REC_TYPE_DELETED = 0x60

# shard_out sentinel codes (ABI 7 partitioned parse)
SHARD_UNROUTABLE = -1  # nameless, or escapes in ns/name (Python routes it)
SHARD_ERROR = -2
SHARD_BOOKMARK = -3


class EventRecord:
    """Compact parse of one watch line (native/ingest.cc): routing strings,
    flags, canonical fingerprints, and pre-formatted container/condition
    blobs (codec renderer input format). `raw` keeps the original line for
    the full-parse fallback."""

    __slots__ = (
        "type", "namespace", "name", "node_name", "phase", "pod_ip",
        "host_ip", "creation", "containers", "init_containers",
        "true_conditions", "flags", "fp_status", "fp_status_nc",
        "fp_spec", "fp_meta_sel", "rv", "raw",
    )

    def __init__(self, type_, ns, name, node, phase, pod_ip, host_ip,
                 creation, ctrs, ictrs, conds, flags, fp_s, fp_nc, fp_spec,
                 fp_meta, rv, raw):
        self.type = type_
        self.namespace = ns
        self.name = name
        self.node_name = node
        self.phase = phase
        self.pod_ip = pod_ip
        self.host_ip = host_ip
        self.creation = creation
        self.containers = ctrs
        self.init_containers = ictrs
        self.true_conditions = conds
        self.flags = flags
        self.fp_status = fp_s
        self.fp_status_nc = fp_nc
        self.fp_spec = fp_spec
        self.fp_meta_sel = fp_meta
        #: metadata.resourceVersion, parsed at metadata's own nesting depth
        #: (a raw substring scan can latch an annotation named
        #: resourceVersion); 0 when absent/non-numeric
        self.rv = rv
        self.raw = raw

    @property
    def ok(self) -> bool:
        return bool(self.flags & REC_OK)


class RouteInfo:
    """Scalar routing summary of one partitioned parse (ingest.cc).
    ``latest_rv`` is the resume revision a full Python walk would commit:
    zeroed whenever the batch carries an ERROR event (rv_dead)."""

    __slots__ = ("latest_rv", "first_error", "bookmarks", "routable",
                 "unrouteable")

    def __init__(self, latest_rv, first_error, bookmarks, routable,
                 unrouteable):
        self.latest_rv = latest_rv
        self.first_error = first_error
        self.bookmarks = bookmarks
        self.routable = routable
        self.unrouteable = unrouteable


class ParsedBatch:
    """One batched kwok_parse_events result; `record(i)` returns a LAZY
    view over the arrays (same attribute surface as EventRecord).

    The numpy outputs are kept (`off_a`/`fp_a`/`flags_a`/`rvs_a` — the
    columnar ingest path gathers straight from them); the per-record list
    mirrors (`off`/`fp`/`flags_arr`/`rvs`, ~10x faster for scalar reads)
    are built eagerly on legacy paths but LAZILY on the partitioned
    router path: the router hands lanes zero-copy sub-batches and never
    pays the tolist — the first lane that needs per-record views converts
    once under `_lists_lock` (drain workers on sibling lanes share it).

    Partitioned parses additionally carry `shard` (per-event lane code),
    `lane_idx`/`lane_off` (per-lane contiguous index runs over routable
    records) and `route_info` (RouteInfo scalars)."""

    __slots__ = (
        "lines", "buf", "n", "off_a", "fp_a", "flags_a", "rvs_a",
        "off", "fp", "flags_arr", "rvs",
        "shard", "lane_idx", "lane_off", "route_info", "_lists_lock",
    )

    def __init__(self, lines, buf, off_a, fp_a, flags_a, rvs_a,
                 lazy=False, partition=None):
        self.lines = lines
        self.buf = buf
        self.n = len(lines)
        self.off_a = off_a
        self.fp_a = fp_a
        self.flags_a = flags_a
        self.rvs_a = rvs_a
        if partition is not None:
            self.shard, self.lane_idx, self.lane_off, self.route_info = (
                partition
            )
        else:
            self.shard = self.lane_idx = self.lane_off = None
            self.route_info = None
        self._lists_lock = threading.Lock()
        if lazy:
            self.off = self.fp = self.flags_arr = self.rvs = None
        else:
            self._build_lists()

    @property
    def partitioned(self) -> bool:
        return self.lane_off is not None

    def _build_lists(self) -> None:
        # numpy scalar indexing costs ~10x a list index and the lazy
        # records index per field: one tolist() per batch beats 11 numpy
        # reads per record (profiled at 18us/event before this)
        self.fp = [row.tolist() for row in self.fp_a]
        self.flags_arr = self.flags_a.tolist()
        self.rvs = self.rvs_a.tolist()
        self.off = self.off_a.tolist()  # set LAST: the presence gate

    def ensure_lists(self) -> None:
        """Idempotent lazy list conversion; safe from concurrent lane
        drain workers (one converts, the rest wait on the lock)."""
        if self.off is not None:
            return
        with self._lists_lock:
            if self.off is None:
                self._build_lists()

    # accessors inline the presence gate: they run O(10k) times per
    # drain on the per-record walk, where an always-early-returning
    # method call is pure dispatch overhead (same unlocked first check
    # ensure_lists itself makes — `off` is set LAST in _build_lists)

    def rv(self, i: int) -> int:
        if self.off is None:
            self.ensure_lists()
        return self.rvs[i]

    def type_bytes(self, i: int) -> bytes:
        if self.off is None:
            self.ensure_lists()
        base = i * _REC_STRINGS
        return self.buf[self.off[base]: self.off[base + 1]]

    def record(self, i: int) -> "_LazyRecord":
        if self.off is None:
            self.ensure_lists()
        return _LazyRecord(self, i)


class _LazyRecord:
    """EventRecord-compatible lazy view into a ParsedBatch. Fields cache
    as plain instance attributes on first access via __getattr__.

    Two tiers keep the steady-state echo flood cheap: flags/ok, the four
    fingerprints, rv, and the identity strings (type/namespace/name)
    resolve individually from the batch arrays — the C parser already
    downgraded escape-carrying records, so `flags` is authoritative
    without scanning any string. Everything else triggers one full
    materialization pass (a survivor will need most fields anyway, and a
    single slicing loop beats eleven lazy slices)."""

    def __init__(self, batch: ParsedBatch, i: int):
        self._b = batch
        self._i = i

    _STR_FIELDS = (
        "type", "namespace", "name", "node_name", "phase", "pod_ip",
        "host_ip", "creation",
    )
    # identity strings the echo-drop path touches; decoded singly so a
    # dropped record never pays the full 11-field pass
    _CHEAP_STR = {"type": 0, "namespace": 1, "name": 2}
    _FP_FIELDS = ("fp_status", "fp_status_nc", "fp_spec", "fp_meta_sel")

    def _materialize(self) -> None:
        b = self._b
        i = self._i
        base = i * _REC_STRINGS
        off = b.off
        buf = b.buf
        d = self.__dict__
        for j, fname in enumerate(self._STR_FIELDS):
            d[fname] = buf[off[base + j]: off[base + j + 1]].decode(
                "utf-8", "surrogateescape"
            )
        d["containers"] = buf[off[base + 8]: off[base + 9]]
        d["init_containers"] = buf[off[base + 9]: off[base + 10]]
        d["true_conditions"] = buf[off[base + 10]: off[base + 11]]
        flag = b.flags_arr[i]
        d["flags"] = flag
        d["ok"] = bool(flag & REC_OK)
        fp = b.fp
        d["fp_status"] = fp[0][i]
        d["fp_status_nc"] = fp[1][i]
        d["fp_spec"] = fp[2][i]
        d["fp_meta_sel"] = fp[3][i]
        d["rv"] = b.rvs[i]

    def __getattr__(self, name: str):
        b = self._b
        i = self._i
        d = self.__dict__
        if name == "flags":
            d["flags"] = v = b.flags_arr[i]
            return v
        if name == "ok":
            d["ok"] = v = bool(b.flags_arr[i] & REC_OK)
            return v
        j = self._CHEAP_STR.get(name)
        if j is not None:
            base = i * _REC_STRINGS
            d[name] = v = b.buf[b.off[base + j]: b.off[base + j + 1]].decode(
                "utf-8", "surrogateescape"
            )
            return v
        if name in self._FP_FIELDS:
            fp = b.fp
            d["fp_status"] = fp[0][i]
            d["fp_status_nc"] = fp[1][i]
            d["fp_spec"] = fp[2][i]
            d["fp_meta_sel"] = fp[3][i]
            return d[name]
        if name == "rv":
            d["rv"] = v = b.rvs[i]
            return v
        if name == "raw":
            d["raw"] = v = bytes(b.lines[i])
            return v
        if name.startswith("_"):
            raise AttributeError(name)
        self._materialize()
        try:
            return d[name]
        except KeyError:
            raise AttributeError(name) from None


class _BlobLines:
    """Sequence view over lines packed as (buf, off) — the raw backing a
    ParsedBatch needs for `.raw` without materializing per-line bytes."""

    __slots__ = ("bbuf", "boff")

    def __init__(self, buf: bytes, off) -> None:
        self.bbuf = buf
        self.boff = off

    def __len__(self) -> int:
        return len(self.boff) - 1

    def __getitem__(self, i: int) -> bytes:
        return self.bbuf[self.boff[i]: self.boff[i + 1]]


class WatchReader:
    """Batched native watch-line reader (ingest.cc watch IO) over a socket
    fd handed off AFTER the Python HTTP handshake. read_batch() returns
    the packed-lines (buf, off) form EventParser.parse_blob consumes —
    skipping both the per-line chunked-read Python loop and the per-line
    bytes objects — or None at end of stream. When a batch was cut short
    by an ERROR event line, `error` carries that line (excluded from the
    returned batch)."""

    def __init__(self, fd: int, initial: bytes = b"",
                 chunked: bool = True) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.kwok_watch_open(
            int(fd), bytes(initial), len(initial), 0 if chunked else 1
        )
        self._cap = 1 << 20
        self._buf = ctypes.create_string_buffer(self._cap)
        self._max_lines = 16384
        self._off = np.zeros(self._max_lines + 1, np.int64)
        self._err = np.zeros(1, np.int32)
        self._need = np.zeros(1, np.int64)
        self.error: bytes | None = None

    def read_batch(self, timeout_s: float = 1.0):
        """(buf, off) with len(off)-1 >= 0 lines (0 = poll timeout; call
        again), or None when the stream is over."""
        self.error = None
        errp = self._err.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            n = self._lib.kwok_watch_read(
                self._h, 1000 if timeout_s is None
                else max(0, int(timeout_s * 1000)),
                self._buf, self._cap,
                _i64p(self._off), self._max_lines, errp, _i64p(self._need),
            )
            if n == -2:  # one line larger than the buffer: grow, retry
                self._cap = max(self._cap * 2, int(self._need[0]) + 4096)
                self._buf = ctypes.create_string_buffer(self._cap)
                continue
            break
        if n < 0:
            return None
        n = int(n)
        off = self._off[: n + 1].tolist()
        # slice the ctypes array directly: ._buf.raw would materialize the
        # FULL capacity (>=1MiB) per call, a real cost on the steady-state
        # one-event-per-poll trickle
        buf = self._buf[: off[-1]] if n else b""
        if self._err[0] and n:
            # the last line is the stream-ending ERROR event
            self.error = buf[off[n - 1]: off[n]]
            off = off[:n]
            buf = buf[: off[-1]] if n > 1 else b""
        return buf, off

    def close(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.kwok_watch_close(h)

    def __del__(self):  # daemon-thread cleanup safety net
        try:
            self.close()
        # kwoklint: disable=silent-except -- __del__ can run at interpreter shutdown where logging/imports are unsafe; a failed close only leaks an already-dying fd
        except Exception:
            pass


class EventParser:
    """Reusable single-line parser: one ctypes call per watch line, with
    preallocated output buffers (the watch threads run this per event, so
    per-call numpy allocation would eat the win)."""

    def __init__(self) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._fp = np.zeros(4, np.uint64)  # status, status_nc, spec, meta
        self._flags = np.zeros(1, np.uint8)
        self._rv = np.zeros(1, np.int64)
        self._str_off = np.zeros(_REC_STRINGS + 1, np.int64)
        self._off = np.zeros(2, np.int64)
        self._cap = 4096
        self._buf = bytearray(self._cap)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        self._fp_ptrs = tuple(
            self._fp[i:].ctypes.data_as(u64p) for i in range(4)
        )
        self._flags_p = self._flags.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)
        )
        self._rv_p = _i64p(self._rv)
        self._off_p = _i64p(self._off)
        self._str_off_p = _i64p(self._str_off)

    def parse_raw_batch(
        self, lines: list, kind: "str | None" = None, n_shards: int = 0
    ) -> "ParsedBatch | None":
        """Parse N watch lines in ONE C call. The per-line path pays a
        ctypes transition + GIL handoff per event; on a busy 1-core host
        that ping-pong (watch thread vs tick thread) dominated the parse
        term of the edge roofline. Batching amortizes it to one call per
        drain — the tick thread parses everything queued since its last
        tick in a single GIL release. Records come back as LAZY views
        (ParsedBatch.record): fingerprints/flags/rv are array reads, and
        string fields decode only on first access — the steady-state echo
        flood is dropped by fingerprint after touching just ns+name.

        With ``kind`` + ``n_shards`` >= 1 the SAME C call also computes
        each event's lane (crc32, identical to rowpool.shard_of) and the
        per-lane contiguous index runs — pre-partitioned routing; see
        ParsedBatch. The list mirrors stay lazy on that path."""
        n = len(lines)
        if n == 0:
            return None
        blob, off = _blob([bytes(x) for x in lines])
        return self._parse_packed(lines, blob, off, n, kind, n_shards)

    def parse_blob(
        self, blob: bytes, off, kind: "str | None" = None,
        n_shards: int = 0,
    ) -> "ParsedBatch | None":
        """parse_raw_batch over lines already packed as (blob, offsets) —
        the native WatchReader's wire format. Skips the per-line list and
        the _blob marshalling loop entirely; `.raw` on records slices the
        source blob lazily."""
        n = len(off) - 1
        if n <= 0:
            return None
        off_arr = np.ascontiguousarray(off, np.int64)
        return self._parse_packed(
            _BlobLines(blob, off), blob, off_arr, n, kind, n_shards
        )

    def _parse_packed(self, lines, blob: bytes, off: np.ndarray, n: int,
                      kind: "str | None" = None, n_shards: int = 0):
        fp = np.zeros((4, n), np.uint64)
        flags = np.zeros(n, np.uint8)
        rvs = np.zeros(n, np.int64)
        str_off = np.zeros(_REC_STRINGS * n + 1, np.int64)
        cap = max(4096, len(blob))
        buf = bytearray(cap)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        ns_arg = int(n_shards) if (n_shards and kind is not None) else 0
        if ns_arg:
            shard = np.zeros(n, np.int32)
            lane_idx = np.zeros(n, np.int32)
            lane_off = np.zeros(ns_arg + 1, np.int64)
            route_info = np.zeros(6, np.int64)
            part_args = (
                1 if kind == "pods" else 0, ns_arg,
                shard.ctypes.data_as(i32p), lane_idx.ctypes.data_as(i32p),
                _i64p(lane_off), _i64p(route_info),
            )
        else:
            part_args = (0, 0, None, None, None, None)
        for _ in range(2):
            need = self._lib.kwok_parse_events(
                blob, _i64p(off), n,
                fp[0].ctypes.data_as(u64p), fp[1].ctypes.data_as(u64p),
                fp[2].ctypes.data_as(u64p), fp[3].ctypes.data_as(u64p),
                flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                _i64p(rvs),
                (ctypes.c_char * cap).from_buffer(buf), cap, _i64p(str_off),
                *part_args,
            )
            if need <= cap:
                break
            cap = int(need) + 1024
            buf = bytearray(cap)
        partition = None
        if ns_arg:
            partition = (
                shard, lane_idx, lane_off.tolist(),
                RouteInfo(*route_info.tolist()[:5]),
            )
        # lazy=partitioned: the router path never touches per-record list
        # views — lanes convert on first need (ParsedBatch.ensure_lists)
        return ParsedBatch(
            lines, bytes(buf[:min(cap, int(need))]), str_off,
            fp, flags, rvs, lazy=bool(ns_arg), partition=partition,
        )

    def parse_batch(self, lines: list) -> "list[EventRecord]":
        """Eager variant of parse_raw_batch (parity tests; small batches)."""
        b = self.parse_raw_batch(lines)
        return [] if b is None else [b.record(i) for i in range(b.n)]

    def parse(self, line: bytes) -> EventRecord:
        self._off[1] = len(line)
        fp = self._fp
        p0, p1, p2, p3 = self._fp_ptrs
        for _ in range(2):
            need = self._lib.kwok_parse_events(
                line, self._off_p, 1,
                p0, p1, p2, p3,
                self._flags_p, self._rv_p,
                (ctypes.c_char * self._cap).from_buffer(self._buf),
                self._cap, self._str_off_p,
                0, 0, None, None, None, None,
            )
            if need <= self._cap:
                break
            self._cap = int(need) + 1024
            self._buf = bytearray(self._cap)
        off = self._str_off
        buf = self._buf
        flags = int(self._flags[0])

        # escape downgrades (REC_OK / REC_STATUS_SCALAR_ONLY cleared for
        # escape-carrying fields) happen in kwok_parse_events (ABI 5) —
        # ONE authoritative copy of the rule, shared with the batch path
        def s(i: int) -> str:
            return bytes(buf[off[i] : off[i + 1]]).decode(
                "utf-8", "surrogateescape"
            )

        def blob(i: int) -> bytes:
            return bytes(buf[off[i] : off[i + 1]])

        return EventRecord(
            s(0), s(1), s(2), s(3), s(4), s(5), s(6), s(7),
            blob(8), blob(9), blob(10),
            flags, int(fp[0]), int(fp[1]), int(fp[2]), int(fp[3]),
            int(self._rv[0]), line,
        )


def fingerprint_statuses(bodies: list) -> "np.ndarray | None":
    """Canonical fingerprint of the `status` subtree of each rendered patch
    body, with the same algorithm the event parser applies to incoming
    objects — equal fingerprints mean the server-side merged status will
    echo back exactly this document."""
    lib = load()
    if lib is None:
        return None
    blob, off = _blob([bytes(b) for b in bodies])
    out = np.zeros(len(bodies), np.uint64)
    lib.kwok_fingerprint_statuses(
        blob, _i64p(off), len(bodies),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out


class Pump:
    """Batched pipelined HTTP client over a fixed pool of keep-alive
    connections (native/pump.cc). send() blocks outside the GIL while the
    whole batch is written/read, so O(10k) unary requests cost one Python
    call. Response bodies are discarded by design: the engine learns state
    from the watch echo; callers only get status codes back."""

    def __init__(
        self, host: str, port: int, nconn: int = 4, header_extra: str = ""
    ) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.kwok_pump_open(
            host.encode(), port, nconn, header_extra.encode()
        )

    @property
    def handle(self) -> int:
        """The raw pump id for fused native calls (emit_pods). Only a
        PLAIN Pump exposes one — wrappers (FaultyPump, FencedPump) are
        detected by isinstance, never by this attribute, so a fused call
        can never tunnel past a fence or the fault plane."""
        return self._handle

    def send(self, requests: list[tuple]) -> "np.ndarray":
        """requests: (method, path, body[, content_type]) tuples; the
        content type defaults to application/json (k8s PATCH verbs need
        their specific patch types — pass them explicitly). Returns the
        per-request HTTP status array (0 = connection failure, caller may
        retry)."""
        n = len(requests)
        status = np.zeros(n, np.int32)
        if n == 0:
            return status
        m_blob, m_off = _blob([r[0].encode() for r in requests])
        p_blob, p_off = _blob([
            r[1].encode() if isinstance(r[1], str) else bytes(r[1])
            for r in requests
        ])
        b_blob, b_off = _blob([bytes(r[2]) for r in requests])
        c_blob, c_off = _blob(
            [(r[3].encode() if len(r) > 3 else b"") for r in requests]
        )
        self._lib.kwok_pump_send(
            self._handle, n,
            m_blob, _i64p(m_off),
            p_blob, _i64p(p_off),
            c_blob, _i64p(c_off),
            b_blob, _i64p(b_off),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return status

    def stats(self) -> dict:
        """Send-path attribution since open (pump.cc, always on): batch
        wall plus the write/read split summed across the pool's
        overlapping connection threads — the pump half of the ISSUE 11
        latency-attribution surface."""
        out = (ctypes.c_double * 5)()
        if self._handle:
            self._lib.kwok_pump_stats(self._handle, out)
        return {
            "batches": int(out[0]),
            "requests": int(out[1]),
            "batch_s": round(out[2], 9),
            "write_s": round(out[3], 9),
            "read_s": round(out[4], 9),
        }

    def close(self) -> None:
        if self._handle:
            self._lib.kwok_pump_close(self._handle)
            self._handle = 0

    def __del__(self):
        try:
            self.close()
        # kwoklint: disable=silent-except -- __del__ can run at interpreter shutdown where logging/imports are unsafe; a failed close only leaks an already-dying fd
        except Exception:
            pass


def apiserver_binary() -> str | None:
    """Path to the native mock kube-apiserver, compiling it on first use
    (mtime-cached next to the source). None when no compiler is available —
    callers fall back to the Python mockserver shim. Disabled along with the
    rest of the native layer by KWOK_TPU_NATIVE=0."""
    global _apiserver_path, _apiserver_tried
    if os.environ.get("KWOK_TPU_NATIVE", "1") == "0":
        return None
    with _apiserver_lock:
        if _apiserver_path is not None or _apiserver_tried:
            return _apiserver_path
        _apiserver_tried = True
        fresh = os.path.exists(_APISERVER_BIN) and os.path.getmtime(
            _APISERVER_BIN
        ) >= os.path.getmtime(_APISERVER_SRC)
        if not fresh:
            cxx = os.environ.get("CXX", "g++")
            cmd = [
                cxx, "-O2", "-std=c++17", "-pthread",
                "-o", _APISERVER_BIN + ".tmp", _APISERVER_SRC,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=180)
            except (OSError, subprocess.SubprocessError) as e:
                logger.info(
                    "native apiserver build failed (%s); using python mock", e
                )
                return None
            os.replace(_APISERVER_BIN + ".tmp", _APISERVER_BIN)
        _apiserver_path = _APISERVER_BIN
        return _apiserver_path


def _blob(items: list[bytes]) -> tuple[bytes, np.ndarray]:
    n = len(items)
    off = np.zeros(n + 1, np.int64)
    if n:
        # map(len, ...) + fromiter stay in C; the old list-comprehension
        # was ~1µs/krow of pure interpreter loop on the emit hot path
        np.cumsum(
            np.fromiter(map(len, items), np.int64, count=n), out=off[1:]
        )
    return b"".join(items), off


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _split(buf: bytearray, off: np.ndarray) -> list[memoryview]:
    """Zero-copy per-row views into the shared output buffer (the HTTP layer
    accepts any bytes-like body)."""
    mv = memoryview(buf)
    off_l = off.tolist()
    return [mv[off_l[i] : off_l[i + 1]] for i in range(len(off_l) - 1)]


def render_heartbeats(
    cond_bits: np.ndarray,
    cond_meta: list[tuple[str, str, str]],
    now: str,
    start_times: list[bytes],
) -> list[bytes] | None:
    """Batch-render node heartbeat status patches; one bytes body per row.

    cond_meta: (type, reason, message) per condition bit, in bit order.
    Returns None when the native library is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    n = len(start_times)
    bits = np.ascontiguousarray(cond_bits, np.uint32)
    meta_items = [s.encode() for t in cond_meta for s in t]
    meta_blob, meta_off = _blob(meta_items)
    start_blob, start_off = _blob(start_times)
    now_b = now.encode()
    out_off = np.zeros(n + 1, np.int64)
    # exact-ish guess: per condition ~120B of literals + the four strings
    per_cond = 128 + len(now_b) + len(meta_blob) // max(1, len(cond_meta))
    cap = max(1024, n * (len(cond_meta) * per_cond + 32) + len(start_blob) * len(cond_meta))
    for _ in range(2):
        out = bytearray(cap)
        need = lib.kwok_render_heartbeats(
            n,
            bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(cond_meta),
            meta_blob, _i64p(meta_off),
            now_b, len(now_b),
            start_blob, _i64p(start_off),
            (ctypes.c_char * len(out)).from_buffer(out), cap, _i64p(out_off),
        )
        if need <= cap:
            return _split(out, out_off)
        cap = need
    raise AssertionError("codec buffer sizing did not converge")


class EmitTable:
    """A compiled EmitTemplates table (models/compiler.py) pinned into
    the contiguous ctypes-ready form kwok_emit_pods consumes — built once
    per engine, shared read-only by every lane's emit worker."""

    __slots__ = (
        "lit_blob", "seg_code", "seg_a", "seg_b", "tpl_off", "tpl_kind",
        "tpl_ready", "phase_tpl", "phase_names",
    )

    def __init__(self, tpl) -> None:
        if load() is None:
            raise RuntimeError("native library unavailable")
        self.lit_blob = bytes(tpl.lit_blob)
        self.seg_code = np.ascontiguousarray(tpl.seg_code, np.int32)
        self.seg_a = np.ascontiguousarray(tpl.seg_a, np.int64)
        self.seg_b = np.ascontiguousarray(tpl.seg_b, np.int64)
        self.tpl_off = np.ascontiguousarray(tpl.tpl_off, np.int64)
        self.tpl_kind = np.ascontiguousarray(tpl.tpl_kind, np.uint8)
        self.tpl_ready = np.ascontiguousarray(tpl.tpl_ready, np.uint8)
        #: plain-int phase id -> template id (list: the emit gather loop
        #: indexes it per row, where numpy scalar reads cost ~10x)
        self.phase_tpl = np.asarray(tpl.phase_tpl, np.int32).tolist()
        self.phase_names = tpl.phase_names


def emit_pods(
    tpl: EmitTable,
    tpl_ids: np.ndarray,
    cond_bits: np.ndarray,
    hosts: list[bytes],
    ips: list[bytes],
    starts: list[bytes],
    ctrs: list[bytes],
    ictrs: list[bytes],
    now: bytes,
    *,
    pump: "Pump | None" = None,
    base: bytes = b"",
    paths: "list[bytes] | None" = None,
    suffix: bytes = b"/status",
    ctype: bytes = b"application/strategic-merge-patch+json",
):
    """Splice per-row values into the AOT patch templates and — with a
    `pump` — ship the batch in the SAME C call (render + fingerprint +
    send, one GIL release end to end).

    Returns ``(bodies, fps, status, need)``: zero-copy per-row body
    views, the canonical status fingerprint per body (echo-drop seeds),
    the per-request HTTP status array (all zeros when no pump was
    given), and the slab size in bytes. None when the library is gone.
    An oversized first guess re-renders into a bigger slab — the C side
    only fingerprints/sends a batch that fit, so the send happens
    exactly once."""
    lib = load()
    if lib is None:
        return None
    n = len(hosts)
    ids = np.ascontiguousarray(tpl_ids, np.int32)
    bits = np.ascontiguousarray(cond_bits, np.uint32)
    host_blob, host_off = _blob(hosts)
    pod_blob, pod_off = _blob(ips)
    start_blob, start_off = _blob(starts)
    ctr_blob, ctr_off = _blob(ctrs)
    ictr_blob, ictr_off = _blob(ictrs)
    if paths is not None:
        path_blob, path_off = _blob(paths)
    else:
        path_blob, path_off = b"", np.zeros(n + 1, np.int64)
    out_off = np.zeros(n + 1, np.int64)
    fps = np.zeros(n, np.uint64)
    status = np.zeros(n, np.int32)
    handle = pump.handle if pump is not None else 0
    i32p = ctypes.POINTER(ctypes.c_int32)
    cap = max(
        2048,
        int(
            n * 512
            + len(ctr_blob) * 4
            + len(ictr_blob) * 4
            + len(start_blob) * 8
        ),
    )
    for _ in range(2):
        out = bytearray(cap)
        need = lib.kwok_emit_pods(
            handle, n,
            ids.ctypes.data_as(i32p),
            bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            tpl.lit_blob,
            tpl.seg_code.ctypes.data_as(i32p),
            _i64p(tpl.seg_a), _i64p(tpl.seg_b), _i64p(tpl.tpl_off),
            tpl.tpl_kind.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            tpl.tpl_ready.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            host_blob, _i64p(host_off),
            pod_blob, _i64p(pod_off),
            start_blob, _i64p(start_off),
            ctr_blob, _i64p(ctr_off),
            ictr_blob, _i64p(ictr_off),
            now, len(now),
            (ctypes.c_char * len(out)).from_buffer(out), cap,
            _i64p(out_off),
            fps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            base, len(base),
            path_blob, _i64p(path_off),
            suffix, len(suffix),
            ctype, len(ctype),
            status.ctypes.data_as(i32p),
        )
        if need <= cap:
            return _split(out, out_off), fps, status, int(need)
        cap = need
    raise AssertionError("codec buffer sizing did not converge")


def render_pod_statuses(
    phase_kind: np.ndarray,
    cond_bits: np.ndarray,
    phase_names: list[bytes],
    cond_names: list[str],
    host_ips: list[bytes],
    pod_ips: list[bytes],
    start_times: list[bytes],
    containers: list[bytes],
    init_containers: list[bytes],
) -> list[bytes] | None:
    """Batch-render pod status patches.

    phase_kind: per row, 0 running-like / 1 terminated-ok / 2 terminated-err.
    containers / init_containers: per-row records "name\\x1fimage\\x1e..." .
    """
    lib = load()
    if lib is None:
        return None
    n = len(phase_names)
    pk = np.ascontiguousarray(phase_kind, np.uint8)
    bits = np.ascontiguousarray(cond_bits, np.uint32)
    phase_blob, phase_off = _blob(phase_names)
    cname_blob, cname_off = _blob([c.encode() for c in cond_names])
    host_blob, host_off = _blob(host_ips)
    pod_blob, pod_off = _blob(pod_ips)
    start_blob, start_off = _blob(start_times)
    ctr_blob, ctr_off = _blob(containers)
    ictr_blob, ictr_off = _blob(init_containers)
    out_off = np.zeros(n + 1, np.int64)
    cap = max(
        2048,
        int(
            n * 512
            + len(ctr_blob) * 4
            + len(ictr_blob) * 4
            + len(start_blob) * 8
        ),
    )
    for _ in range(2):
        out = bytearray(cap)
        need = lib.kwok_render_pod_statuses(
            n,
            pk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            phase_blob, _i64p(phase_off),
            len(cond_names), cname_blob, _i64p(cname_off),
            host_blob, _i64p(host_off),
            pod_blob, _i64p(pod_off),
            start_blob, _i64p(start_off),
            ctr_blob, _i64p(ctr_off),
            ictr_blob, _i64p(ictr_off),
            (ctypes.c_char * len(out)).from_buffer(out), cap, _i64p(out_off),
        )
        if need <= cap:
            return _split(out, out_off)
        cap = need
    raise AssertionError("codec buffer sizing did not converge")
