// kwok_tpu native ingest: watch-event extraction + canonical fingerprints.
//
// The engine's ingest edge was the scale wall (at 50k pods the tick thread
// spent ~85% of its time in per-event json.loads + repair-path render/merge
// on events that are echoes of the engine's own patches). This library
// parses a watch-event line ONCE in C++ and returns:
//
//   - the routing fields the engine needs (type, namespace, name, nodeName,
//     deletion/finalizer flags),
//   - order-insensitive canonical fingerprints of the subtrees whose change
//     forces full (Python) processing: status, status-minus-conditions
//     (nodes: the reference's no-op check pins conditions, so heartbeat
//     echoes only differ there — node_controller.go:377), spec, and the
//     selector-relevant metadata (labels+annotations+deletion+finalizers).
//
// The engine then DROPS events whose fingerprints prove the reference's
// render->merge->compare pipeline would conclude "no patch needed", and
// fully parses only the survivors. Dropping is always the conservative
// direction: any mismatch or parse surprise routes to the Python path.
//
// Fingerprint: objects combine members with XOR (insertion-order
// invariant: the server may store keys in a different order than our
// renderer emits), arrays combine in order, scalars hash their raw token
// text. Two serializations of the same document agree as long as they
// escape strings identically — when they don't, fingerprints differ and
// the engine just takes the slow path.
//
// Build: part of libkwokcodec.so (see native/__init__.py _build).

#include <climits>
#include <cstdint>
#include <cstring>
#include <utility>
#include <vector>

namespace {

struct Cursor {
  const char* p;
  const char* end;
  bool ok = true;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool at(char c) { return p < end && *p == c; }
  void expect(char c) {
    if (at(c)) p++;
    else ok = false;
  }
};

constexpr uint64_t FNV_OFFSET = 1469598103934665603ull;
constexpr uint64_t FNV_PRIME = 1099511628211ull;
constexpr uint64_t OBJ_SEED = 0x9e3779b97f4a7c15ull;
constexpr uint64_t ARR_SEED = 0xc2b2ae3d27d4eb4full;

inline uint64_t fnv(const char* s, int64_t n, uint64_t h = FNV_OFFSET) {
  for (int64_t i = 0; i < n; i++) {
    h ^= (unsigned char)s[i];
    h *= FNV_PRIME;
  }
  return h;
}

inline uint64_t mix(uint64_t a, uint64_t b) {
  uint64_t x = a ^ (b + 0x9e3779b97f4a7c15ull + (a << 6) + (a >> 2));
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdull;
  x ^= x >> 33;
  return x;
}

// Raw string token: bytes between the quotes, escapes NOT decoded.
// Returns [start, len) into the buffer; cursor ends after closing quote.
bool raw_string(Cursor& c, const char** start, int64_t* len) {
  if (!c.at('"')) {
    c.ok = false;
    return false;
  }
  c.p++;
  *start = c.p;
  while (c.p < c.end) {
    if (*c.p == '\\') {
      c.p += 2;
      continue;
    }
    if (*c.p == '"') {
      *len = c.p - *start;
      c.p++;
      return true;
    }
    c.p++;
  }
  c.ok = false;
  return false;
}

uint64_t fp_value(Cursor& c);

uint64_t fp_object(Cursor& c) {
  c.expect('{');
  c.ws();
  uint64_t h = OBJ_SEED;
  if (c.at('}')) {
    c.p++;
    return h;
  }
  while (c.ok) {
    c.ws();
    const char* ks;
    int64_t kn;
    if (!raw_string(c, &ks, &kn)) return h;
    c.ws();
    c.expect(':');
    c.ws();
    uint64_t kv = mix(fnv(ks, kn), fp_value(c));
    h ^= kv;  // XOR: member order must not matter
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect('}');
  return h;
}

uint64_t fp_array(Cursor& c) {
  c.expect('[');
  c.ws();
  uint64_t h = ARR_SEED;
  if (c.at(']')) {
    c.p++;
    return h;
  }
  while (c.ok) {
    c.ws();
    h = mix(h, fp_value(c));  // order matters for arrays
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect(']');
  return h;
}

uint64_t fp_value(Cursor& c) {
  c.ws();
  if (c.p >= c.end) {
    c.ok = false;
    return 0;
  }
  switch (*c.p) {
    case '{': return fp_object(c);
    case '[': return fp_array(c);
    case '"': {
      const char* s;
      int64_t n;
      raw_string(c, &s, &n);
      return fnv(s, n) ^ 0x5bd1e995u;
    }
    default: {
      const char* s = c.p;
      while (c.p < c.end && *c.p != ',' && *c.p != '}' && *c.p != ']' &&
             *c.p != ' ' && *c.p != '\t' && *c.p != '\n' && *c.p != '\r')
        c.p++;
      return fnv(s, c.p - s);
    }
  }
}

void skip_value(Cursor& c) {
  c.ws();
  if (c.p >= c.end) {
    c.ok = false;
    return;
  }
  switch (*c.p) {
    case '{': {
      c.p++;
      int depth = 1;
      while (c.p < c.end && depth) {
        if (*c.p == '"') {
          const char* s;
          int64_t n;
          raw_string(c, &s, &n);
          continue;
        }
        if (*c.p == '{') depth++;
        else if (*c.p == '}') depth--;
        c.p++;
      }
      if (depth) c.ok = false;
      return;
    }
    case '[': {
      c.p++;
      int depth = 1;
      while (c.p < c.end && depth) {
        if (*c.p == '"') {
          const char* s;
          int64_t n;
          raw_string(c, &s, &n);
          continue;
        }
        if (*c.p == '[') depth++;
        else if (*c.p == ']') depth--;
        c.p++;
      }
      if (depth) c.ok = false;
      return;
    }
    case '"': {
      const char* s;
      int64_t n;
      raw_string(c, &s, &n);
      return;
    }
    default:
      while (c.p < c.end && *c.p != ',' && *c.p != '}' && *c.p != ']' &&
             *c.p != ' ' && *c.p != '\t' && *c.p != '\n' && *c.p != '\r')
        c.p++;
  }
}

struct Span {
  const char* p = nullptr;
  int64_t n = 0;
  bool present() const { return p != nullptr; }
};

// zlib-compatible CRC-32 (IEEE, reflected): the routing hash MUST equal
// Python's zlib.crc32 over the same bytes, because rowpool.shard_of is the
// key->lane contract the lane pools are built on. Table built on first use.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xedb88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

const uint32_t* crc32_table() {
  // C++11 magic static: ctypes drops the GIL around kwok_parse_events,
  // so two engines in one process can race the first use — a plain
  // ready-flag would let a thread read the table before its stores are
  // visible and route a key to the wrong lane
  static const Crc32Table table;
  return table.t;
}

inline uint32_t crc32_update(uint32_t crc, const char* p, int64_t n) {
  const uint32_t* t = crc32_table();
  for (int64_t i = 0; i < n; i++)
    crc = t[(crc ^ (unsigned char)p[i]) & 0xffu] ^ (crc >> 8);
  return crc;
}

// shard_of(key, n) for the two key shapes the row pools use: node keys are
// the name; pod keys are (namespace or "default", name) joined by \x1f —
// exactly rowpool.shard_of's "\x1f".join(...).encode(). Raw token bytes are
// what the Python router hashes too (decode("utf-8")/encode() round-trips
// them), so the mapping is provably unchanged.
inline int32_t shard_of_event(const Span& ns, const Span& name,
                              int kind_is_pods, int32_t n_shards) {
  if (n_shards <= 1) return 0;
  uint32_t crc = 0xffffffffu;
  if (kind_is_pods) {
    if (ns.n > 0) crc = crc32_update(crc, ns.p, ns.n);
    else crc = crc32_update(crc, "default", 7);
    crc = crc32_update(crc, "\x1f", 1);
  }
  crc = crc32_update(crc, name.p, name.n);
  return (int32_t)((crc ^ 0xffffffffu) % (uint32_t)n_shards);
}

bool span_eq(const Span& s, const char* lit) {
  int64_t n = (int64_t)strlen(lit);
  return s.n == n && memcmp(s.p, lit, n) == 0;
}

// One parsed watch event (or list item).
struct Event {
  Span type;       // ADDED / MODIFIED / DELETED / ...
  Span name, ns, node_name, phase, pod_ip, host_ip, creation;
  bool has_deletion = false;
  bool has_finalizers = false;
  bool has_readiness_gates = false;
  bool status_scalar_only = true;  // keys subset of {phase,hostIP,podIP,startTime}
  uint64_t fp_status = 0;
  uint64_t fp_status_nc = 0;  // status minus top-level "conditions"
  uint64_t fp_spec = 0;
  uint64_t fp_meta_sel = 0;   // labels+annotations+deletion+finalizers
  int64_t rv = 0;             // metadata.resourceVersion (0 if absent)
  std::vector<std::pair<Span, Span>> containers;       // (name, image)
  std::vector<std::pair<Span, Span>> init_containers;  // (name, image)
  std::vector<Span> true_conditions;                   // types with status True
  bool ok = false;
};

// Fingerprint an array of container objects while extracting (name, image)
// span pairs — same fp algorithm as fp_array/fp_object.
uint64_t fp_container_array(Cursor& c,
                            std::vector<std::pair<Span, Span>>* out) {
  c.ws();
  if (!c.at('[')) return fp_value(c);
  c.p++;
  uint64_t h = ARR_SEED;
  c.ws();
  if (c.at(']')) {
    c.p++;
    return h;
  }
  while (c.ok) {
    c.ws();
    if (!c.at('{')) {
      h = mix(h, fp_value(c));
    } else {
      c.p++;
      uint64_t eh = OBJ_SEED;
      Span cname, cimage;
      c.ws();
      if (c.at('}')) {
        c.p++;
      } else {
        while (c.ok) {
          c.ws();
          const char* ks;
          int64_t kn;
          if (!raw_string(c, &ks, &kn)) break;
          c.ws();
          c.expect(':');
          c.ws();
          Span key{ks, kn};
          if (span_eq(key, "name") && c.at('"')) {
            raw_string(c, &cname.p, &cname.n);
            eh ^= mix(fnv(ks, kn), fnv(cname.p, cname.n) ^ 0x5bd1e995u);
          } else if (span_eq(key, "image") && c.at('"')) {
            raw_string(c, &cimage.p, &cimage.n);
            eh ^= mix(fnv(ks, kn), fnv(cimage.p, cimage.n) ^ 0x5bd1e995u);
          } else {
            eh ^= mix(fnv(ks, kn), fp_value(c));
          }
          c.ws();
          if (c.at(',')) {
            c.p++;
            continue;
          }
          break;
        }
        c.expect('}');
      }
      if (out) out->emplace_back(cname, cimage);
      h = mix(h, eh);
    }
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect(']');
  return h;
}

// Fingerprint the conditions array while collecting the True-status types.
uint64_t fp_conditions_array(Cursor& c, std::vector<Span>* out) {
  c.ws();
  if (!c.at('[')) return fp_value(c);
  c.p++;
  uint64_t h = ARR_SEED;
  c.ws();
  if (c.at(']')) {
    c.p++;
    return h;
  }
  while (c.ok) {
    c.ws();
    if (!c.at('{')) {
      h = mix(h, fp_value(c));
    } else {
      c.p++;
      uint64_t eh = OBJ_SEED;
      Span ctype, cstatus;
      c.ws();
      if (c.at('}')) {
        c.p++;
      } else {
        while (c.ok) {
          c.ws();
          const char* ks;
          int64_t kn;
          if (!raw_string(c, &ks, &kn)) break;
          c.ws();
          c.expect(':');
          c.ws();
          Span key{ks, kn};
          if (span_eq(key, "type") && c.at('"')) {
            raw_string(c, &ctype.p, &ctype.n);
            eh ^= mix(fnv(ks, kn), fnv(ctype.p, ctype.n) ^ 0x5bd1e995u);
          } else if (span_eq(key, "status") && c.at('"')) {
            raw_string(c, &cstatus.p, &cstatus.n);
            eh ^= mix(fnv(ks, kn), fnv(cstatus.p, cstatus.n) ^ 0x5bd1e995u);
          } else {
            eh ^= mix(fnv(ks, kn), fp_value(c));
          }
          c.ws();
          if (c.at(',')) {
            c.p++;
            continue;
          }
          break;
        }
        c.expect('}');
      }
      if (out && ctype.present() && span_eq(cstatus, "True"))
        out->push_back(ctype);
      h = mix(h, eh);
    }
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect(']');
  return h;
}

// Fingerprint the status object while noting phase/podIP/hostIP spans and
// computing the minus-conditions variant.
void walk_status(Cursor& c, Event& ev) {
  c.ws();
  if (!c.at('{')) {  // status may be null/absent-shaped
    ev.fp_status = fp_value(c);
    ev.fp_status_nc = ev.fp_status;
    return;
  }
  c.p++;
  uint64_t h = OBJ_SEED, hnc = OBJ_SEED;
  c.ws();
  if (c.at('}')) {
    c.p++;
    ev.fp_status = h;
    ev.fp_status_nc = hnc;
    return;
  }
  while (c.ok) {
    c.ws();
    const char* ks;
    int64_t kn;
    if (!raw_string(c, &ks, &kn)) break;
    c.ws();
    c.expect(':');
    c.ws();
    Span key{ks, kn};
    if (span_eq(key, "phase") && c.at('"')) {
      raw_string(c, &ev.phase.p, &ev.phase.n);
      uint64_t kv = mix(fnv(ks, kn), fnv(ev.phase.p, ev.phase.n) ^ 0x5bd1e995u);
      h ^= kv;
      hnc ^= kv;
    } else if (span_eq(key, "podIP") && c.at('"')) {
      raw_string(c, &ev.pod_ip.p, &ev.pod_ip.n);
      uint64_t kv =
          mix(fnv(ks, kn), fnv(ev.pod_ip.p, ev.pod_ip.n) ^ 0x5bd1e995u);
      h ^= kv;
      hnc ^= kv;
    } else if (span_eq(key, "hostIP") && c.at('"')) {
      raw_string(c, &ev.host_ip.p, &ev.host_ip.n);
      uint64_t kv =
          mix(fnv(ks, kn), fnv(ev.host_ip.p, ev.host_ip.n) ^ 0x5bd1e995u);
      h ^= kv;
      hnc ^= kv;
    } else if (span_eq(key, "conditions")) {
      uint64_t vfp = fp_conditions_array(c, &ev.true_conditions);
      h ^= mix(fnv(ks, kn), vfp);  // excluded from hnc by definition
      ev.status_scalar_only = false;
    } else {
      uint64_t vfp = fp_value(c);
      uint64_t kv = mix(fnv(ks, kn), vfp);
      h ^= kv;
      hnc ^= kv;
      if (!span_eq(key, "startTime")) ev.status_scalar_only = false;
    }
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect('}');
  ev.fp_status = h;
  ev.fp_status_nc = hnc;
}

void walk_metadata(Cursor& c, Event& ev) {
  c.ws();
  if (!c.at('{')) {
    skip_value(c);
    return;
  }
  c.p++;
  uint64_t sel = OBJ_SEED;
  c.ws();
  if (c.at('}')) {
    c.p++;
    ev.fp_meta_sel = sel;
    return;
  }
  while (c.ok) {
    c.ws();
    const char* ks;
    int64_t kn;
    if (!raw_string(c, &ks, &kn)) break;
    c.ws();
    c.expect(':');
    c.ws();
    Span key{ks, kn};
    if (span_eq(key, "name") && c.at('"')) {
      raw_string(c, &ev.name.p, &ev.name.n);
    } else if (span_eq(key, "namespace") && c.at('"')) {
      raw_string(c, &ev.ns.p, &ev.ns.n);
    } else if (span_eq(key, "creationTimestamp") && c.at('"')) {
      raw_string(c, &ev.creation.p, &ev.creation.n);
    } else if (span_eq(key, "resourceVersion") && c.at('"')) {
      // parsed HERE, at metadata's own nesting depth: a raw substring
      // scan can latch an annotation literally named resourceVersion
      // when annotations serialize before metadata.resourceVersion
      // (insertion-ordered servers do this). Server-stamped digits;
      // anything non-numeric stays 0.
      Span rvs;
      raw_string(c, &rvs.p, &rvs.n);
      int64_t v = 0;
      bool num = rvs.n > 0;
      for (int64_t j = 0; j < rvs.n && num; j++) {
        char ch = rvs.p[j];
        if (ch < '0' || ch > '9' ||
            v > (INT64_MAX - (ch - '0')) / 10) {
          // non-digit, or the value would overflow int64 (etcd revisions
          // are int64; anything wider is garbage): leave rv = 0 rather
          // than latch a wrapped/negative resume revision
          num = false;
        } else {
          v = v * 10 + (ch - '0');
        }
      }
      if (num) ev.rv = v;
    } else if (span_eq(key, "deletionTimestamp")) {
      ev.has_deletion = !(c.p + 4 <= c.end && memcmp(c.p, "null", 4) == 0);
      skip_value(c);
    } else if (span_eq(key, "finalizers")) {
      const char* before = c.p;
      skip_value(c);
      // non-empty array?
      for (const char* q = before; q < c.p; q++) {
        if (*q == '[') continue;
        if (*q == ' ' || *q == '\n' || *q == '\t' || *q == '\r') continue;
        ev.has_finalizers = (*q != ']');
        break;
      }
      sel ^= mix(fnv(ks, kn), fnv(before, c.p - before));
    } else if (span_eq(key, "labels") || span_eq(key, "annotations")) {
      uint64_t vfp = fp_value(c);
      sel ^= mix(fnv(ks, kn), vfp);
    } else {
      skip_value(c);
    }
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect('}');
  sel = mix(sel, (uint64_t)ev.has_deletion << 1 | (uint64_t)ev.has_finalizers);
  ev.fp_meta_sel = sel;
}

void walk_spec(Cursor& c, Event& ev) {
  c.ws();
  if (!c.at('{')) {
    ev.fp_spec = fp_value(c);
    return;
  }
  c.p++;
  uint64_t h = OBJ_SEED;
  c.ws();
  if (c.at('}')) {
    c.p++;
    ev.fp_spec = h;
    return;
  }
  while (c.ok) {
    c.ws();
    const char* ks;
    int64_t kn;
    if (!raw_string(c, &ks, &kn)) break;
    c.ws();
    c.expect(':');
    c.ws();
    Span key{ks, kn};
    if (span_eq(key, "nodeName") && c.at('"')) {
      raw_string(c, &ev.node_name.p, &ev.node_name.n);
      h ^= mix(fnv(ks, kn),
               fnv(ev.node_name.p, ev.node_name.n) ^ 0x5bd1e995u);
    } else if (span_eq(key, "containers")) {
      h ^= mix(fnv(ks, kn), fp_container_array(c, &ev.containers));
    } else if (span_eq(key, "initContainers")) {
      h ^= mix(fnv(ks, kn), fp_container_array(c, &ev.init_containers));
    } else if (span_eq(key, "readinessGates")) {
      const char* before = c.p;
      uint64_t vfp = fp_value(c);
      h ^= mix(fnv(ks, kn), vfp);
      for (const char* q = before; q < c.p; q++) {
        if (*q == '[') continue;
        if (*q == ' ' || *q == '\n' || *q == '\t' || *q == '\r') continue;
        ev.has_readiness_gates = (*q != ']');
        break;
      }
    } else {
      uint64_t vfp = fp_value(c);
      h ^= mix(fnv(ks, kn), vfp);
    }
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect('}');
  ev.fp_spec = h;
}

// Parse {"type":"...","object":{...}} (a watch line) or a bare object (a
// List item). Populates ev; ev.ok=false routes the caller to Python.
void parse_event(const char* data, int64_t n, Event& ev) {
  Cursor c{data, data + n};
  c.ws();
  if (!c.at('{')) return;
  c.p++;
  bool saw_object = false;
  while (c.ok) {
    c.ws();
    const char* ks;
    int64_t kn;
    if (!raw_string(c, &ks, &kn)) break;
    c.ws();
    c.expect(':');
    c.ws();
    Span key{ks, kn};
    if (span_eq(key, "type") && c.at('"')) {
      raw_string(c, &ev.type.p, &ev.type.n);
    } else if (span_eq(key, "object")) {
      // nested object document
      c.ws();
      if (!c.at('{')) {
        skip_value(c);
      } else {
        saw_object = true;
        c.p++;
        while (c.ok) {
          c.ws();
          const char* oks;
          int64_t okn;
          if (!raw_string(c, &oks, &okn)) break;
          c.ws();
          c.expect(':');
          Span okey{oks, okn};
          if (span_eq(okey, "metadata")) walk_metadata(c, ev);
          else if (span_eq(okey, "spec")) walk_spec(c, ev);
          else if (span_eq(okey, "status")) walk_status(c, ev);
          else skip_value(c);
          c.ws();
          if (c.at(',')) {
            c.p++;
            continue;
          }
          break;
        }
        c.expect('}');
      }
    } else if (span_eq(key, "metadata")) {
      // bare object form (List item)
      walk_metadata(c, ev);
      saw_object = true;
    } else if (span_eq(key, "spec")) {
      walk_spec(c, ev);
      saw_object = true;
    } else if (span_eq(key, "status")) {
      walk_status(c, ev);
      saw_object = true;
    } else {
      skip_value(c);
    }
    c.ws();
    if (c.at(',')) {
      c.p++;
      continue;
    }
    break;
  }
  c.expect('}');
  ev.ok = c.ok && saw_object && ev.name.present();
}

}  // namespace

extern "C" {

// Parse n event lines (concatenated, offsets delimit). Fixed-width outputs
// per event; string fields are copied into str_out with per-event offsets
// for (type, ns, name, nodeName, phase, podIP, hostIP, creationTimestamp,
// containers, initContainers, trueConditions) — 11 strings per event, so
// str_off has 11*n+1 entries. Containers are "name\x1fimage" records
// joined by \x1e (the codec renderer's input format); trueConditions are
// condition types with status True joined by \x1f. Returns total string
// bytes needed (if > str_cap, call again with a bigger buffer).
// flags bit 0 = parse ok, 1 = has_deletion, 2 = has_finalizers,
// 3 = has_readiness_gates, 4 = status has scalar-replace keys only;
// bits 5-6 = event type code (1 ADDED, 2 MODIFIED, 3 DELETED, 0 other).
//
// Pre-partitioned routing (ABI 7): with n_shards >= 1 the parser also
// computes each event's lane (shard_of_event — the same crc32 mapping as
// rowpool.shard_of) and counting-sorts routable records into per-lane
// contiguous index runs, so the engine's router hands each lane ONE
// zero-copy sub-batch instead of hashing+dispatching per event in Python:
//   shard_out[i]: lane id >= 0, or -1 (record without a name, or with
//                 JSON escapes in ns/name — either way only the Python
//                 router can place it), -2 (ERROR event), -3 (BOOKMARK)
//   lane_idx[ /  lane_off ]: routable record indexes partitioned by lane
//                 (stable: original order within each lane); lane_off has
//                 n_shards+1 entries
//   route_info: [0] the resume revision a full Python walk would commit:
//               the latest metadata rv, ZEROED once an ERROR appears
//               (rv_dead — nothing before or after a stream error
//               commits), [1] index of the first ERROR event or -1,
//               [2] bookmark count,
//               [3] routable count, [4] nameless-record count
// With n_shards == 0 the four routing outputs may be null (legacy paths).
int64_t kwok_parse_events(
    const char* blob, const int64_t* off, int32_t n,
    uint64_t* fp_status, uint64_t* fp_status_nc, uint64_t* fp_spec,
    uint64_t* fp_meta_sel, uint8_t* flags, int64_t* rv_out,
    char* str_out, int64_t str_cap, int64_t* str_off,
    int32_t kind_is_pods, int32_t n_shards,
    int32_t* shard_out, int32_t* lane_idx, int64_t* lane_off,
    int64_t* route_info) {
  int64_t used = 0;
  auto put_bytes = [&](const char* p, int64_t len) {
    if (p && len > 0) {
      if (used + len <= str_cap) memcpy(str_out + used, p, len);
      used += len;
    }
  };
  auto put = [&](const Span& s, int64_t slot) {
    str_off[slot] = used;
    put_bytes(s.p, s.n);
  };
  auto put_ctrs = [&](const std::vector<std::pair<Span, Span>>& cs,
                      int64_t slot) {
    str_off[slot] = used;
    for (size_t j = 0; j < cs.size(); j++) {
      if (j) put_bytes("\x1e", 1);
      put_bytes(cs[j].first.p, cs[j].first.n);
      put_bytes("\x1f", 1);
      put_bytes(cs[j].second.p, cs[j].second.n);
    }
  };
  auto has_esc = [](const Span& s) {
    return s.p && s.n > 0 && memchr(s.p, '\\', (size_t)s.n) != nullptr;
  };
  int64_t latest_rv = 0;
  int64_t first_error = -1;
  int64_t bookmarks = 0;
  int64_t routable = 0;
  int64_t nameless = 0;
  for (int32_t i = 0; i < n; i++) {
    Event ev;
    parse_event(blob + off[i], off[i + 1] - off[i], ev);
    fp_status[i] = ev.fp_status;
    fp_status_nc[i] = ev.fp_status_nc;
    fp_spec[i] = ev.fp_spec;
    fp_meta_sel[i] = ev.fp_meta_sel;
    rv_out[i] = ev.rv;
    uint8_t tcode = 0;
    if (span_eq(ev.type, "ADDED")) tcode = 1;
    else if (span_eq(ev.type, "MODIFIED")) tcode = 2;
    else if (span_eq(ev.type, "DELETED")) tcode = 3;
    if (n_shards >= 1) {
      int32_t shard;
      if (span_eq(ev.type, "ERROR")) {
        shard = -2;
        if (first_error < 0) {
          first_error = i;
          // match the Python walk exactly: an ERROR zeroes the pending
          // resume revision (rv_dead) — the pre-error rv must not be
          // committable either
          latest_rv = 0;
        }
      } else if (span_eq(ev.type, "BOOKMARK")) {
        shard = -3;
        bookmarks++;
      } else if (ev.name.n > 0 &&
                 !memchr(ev.name.p, '\\', (size_t)ev.name.n) &&
                 !(ev.ns.n > 0 &&
                   memchr(ev.ns.p, '\\', (size_t)ev.ns.n))) {
        shard = shard_of_event(ev.ns, ev.name, kind_is_pods, n_shards);
        routable++;
      } else {
        // no name, or JSON escapes in ns/name: the Python router hashes
        // the DECODED string while we'd hash raw token bytes — the same
        // key could land on two different lanes across the fast/slow
        // paths. Classify as unrouteable so the whole batch takes the
        // per-record Python walk (one router, one mapping).
        shard = -1;
        nameless++;
      }
      shard_out[i] = shard;
      // the resume-revision walk _drain_flush_kind used to do per record:
      // nothing after a stream ERROR counts
      if (ev.rv && first_error < 0) latest_rv = ev.rv;
    }
    // JSON escapes in any extracted string downgrade the record: the
    // fast path ships raw token bytes, which would mis-render escaped
    // values (the Python side used to re-scan every field for this;
    // doing it here keeps `flags` authoritative so echo-dropped events
    // never materialize their strings at all). Escapes in the container/
    // condition blobs additionally invalidate the scalar-status claim.
    bool esc_str = has_esc(ev.type) || has_esc(ev.ns) || has_esc(ev.name) ||
                   has_esc(ev.node_name) || has_esc(ev.phase) ||
                   has_esc(ev.pod_ip) || has_esc(ev.host_ip) ||
                   has_esc(ev.creation);
    bool esc_blob = false;
    for (const auto& pr : ev.containers)
      esc_blob = esc_blob || has_esc(pr.first) || has_esc(pr.second);
    for (const auto& pr : ev.init_containers)
      esc_blob = esc_blob || has_esc(pr.first) || has_esc(pr.second);
    for (const auto& s : ev.true_conditions)
      esc_blob = esc_blob || has_esc(s);
    uint8_t f = (uint8_t)(ev.ok | (ev.has_deletion << 1) |
                          (ev.has_finalizers << 2) |
                          (ev.has_readiness_gates << 3) |
                          (ev.status_scalar_only << 4));
    if (esc_str || esc_blob) f = (uint8_t)(f & ~1u);
    if (esc_blob) f = (uint8_t)(f & ~16u);
    f = (uint8_t)(f | (tcode << 5));
    flags[i] = f;
    int64_t base = (int64_t)i * 11;
    put(ev.type, base + 0);
    put(ev.ns, base + 1);
    put(ev.name, base + 2);
    put(ev.node_name, base + 3);
    put(ev.phase, base + 4);
    put(ev.pod_ip, base + 5);
    put(ev.host_ip, base + 6);
    put(ev.creation, base + 7);
    put_ctrs(ev.containers, base + 8);
    put_ctrs(ev.init_containers, base + 9);
    str_off[base + 10] = used;
    for (size_t j = 0; j < ev.true_conditions.size(); j++) {
      if (j) put_bytes("\x1f", 1);
      put_bytes(ev.true_conditions[j].p, ev.true_conditions[j].n);
    }
  }
  str_off[(int64_t)n * 11] = used;
  if (n_shards >= 1) {
    // counting sort of routable records into per-lane contiguous runs
    // (stable: original order within each lane == the order the Python
    // per-event router would have enqueued them)
    for (int32_t s = 0; s <= n_shards; s++) lane_off[s] = 0;
    for (int32_t i = 0; i < n; i++)
      if (shard_out[i] >= 0) lane_off[shard_out[i] + 1]++;
    for (int32_t s = 0; s < n_shards; s++) lane_off[s + 1] += lane_off[s];
    std::vector<int64_t> cursor(lane_off, lane_off + n_shards);
    for (int32_t i = 0; i < n; i++) {
      int32_t s = shard_out[i];
      if (s >= 0) lane_idx[cursor[s]++] = i;
    }
    route_info[0] = latest_rv;
    route_info[1] = first_error;
    route_info[2] = bookmarks;
    route_info[3] = routable;
    route_info[4] = nameless;
  }
  return used;
}

// Fingerprint the "status" subtree of each rendered patch body
// ({"status":{...}}), with the SAME algorithm the event parser uses — the
// engine stores these as the expected post-patch status fingerprint.
void kwok_fingerprint_statuses(const char* blob, const int64_t* off,
                               int32_t n, uint64_t* out) {
  for (int32_t i = 0; i < n; i++) {
    Cursor c{blob + off[i], blob + off[i + 1]};
    c.ws();
    uint64_t fp = 0;
    if (c.at('{')) {
      c.p++;
      while (c.ok) {
        c.ws();
        const char* ks;
        int64_t kn;
        if (!raw_string(c, &ks, &kn)) break;
        c.ws();
        c.expect(':');
        if (kn == 6 && memcmp(ks, "status", 6) == 0) {
          Event ev;
          walk_status(c, ev);
          fp = ev.fp_status;
        } else {
          skip_value(c);
        }
        c.ws();
        if (c.at(',')) {
          c.p++;
          continue;
        }
        break;
      }
    }
    out[i] = fp;
  }
}

}  // extern "C"

// --------------------------------------------------------------- watch IO
// Native watch-line reader: owns the socket AFTER the Python client has
// completed the HTTP handshake (headers consumed; any body bytes already
// buffered on the Python side are handed over verbatim). De-chunks the
// transfer encoding and returns BATCHES of newline-delimited event lines
// per call — the Python per-line chunked-read loop (http.client readline,
// one lock dance + several method calls per event) was the largest
// remaining per-event Python term on the ingest edge. Parsing semantics
// are untouched: lines go to the same EventParser, ERROR handling and
// resume-revision bookkeeping stay in the engine.

#include <errno.h>
#include <poll.h>
#include <sys/socket.h>

#include <string>

namespace {

struct WatchReader {
  int fd;
  std::string in;    // raw socket bytes, not yet de-chunked
  size_t in_off = 0;
  std::string body;  // de-chunked bytes pending line split
  size_t body_off = 0;
  // -1: awaiting a chunk-size line; -2: awaiting the CRLF after a chunk
  // payload; >=0: payload bytes left in the current chunk
  long long chunk_left = -1;
  bool identity = false;  // no Transfer-Encoding: body runs to EOF
  bool eof = false;
};

// moves complete chunks from `in` to `body`; tolerant of any chunk/event
// alignment (an event may span chunks; a chunk may carry many events)
void dechunk(WatchReader& r) {
  if (r.identity) {
    r.body.append(r.in, r.in_off, std::string::npos);
    r.in.clear();
    r.in_off = 0;
    return;
  }
  while (r.in_off < r.in.size()) {
    if (r.chunk_left == -1) {
      size_t crlf = r.in.find("\r\n", r.in_off);
      if (crlf == std::string::npos) break;  // size line incomplete
      long long size = 0;
      bool any = false;
      for (size_t p = r.in_off; p < crlf; p++) {
        char c = r.in[p];
        int v;
        if (c >= '0' && c <= '9') v = c - '0';
        else if (c >= 'a' && c <= 'f') v = c - 'a' + 10;
        else if (c >= 'A' && c <= 'F') v = c - 'A' + 10;
        else break;  // chunk extension (";...") or junk: stop at it
        size = size * 16 + v;
        any = true;
      }
      r.in_off = crlf + 2;
      if (!any || size == 0) {
        // malformed size line or the terminating 0-chunk (trailers
        // ignored): the stream is over either way
        r.eof = true;
        r.in.clear();
        r.in_off = 0;
        return;
      }
      r.chunk_left = size;
    } else if (r.chunk_left > 0) {
      size_t avail = r.in.size() - r.in_off;
      size_t take = avail < (size_t)r.chunk_left ? avail : (size_t)r.chunk_left;
      r.body.append(r.in, r.in_off, take);
      r.in_off += take;
      r.chunk_left -= (long long)take;
      if (r.chunk_left == 0) r.chunk_left = -2;
      if (r.in_off >= r.in.size()) break;
    } else {  // -2: CRLF after payload
      if (r.in.size() - r.in_off < 2) break;
      r.in_off += 2;
      r.chunk_left = -1;
    }
  }
  if (r.in_off) {
    r.in.erase(0, r.in_off);
    r.in_off = 0;
  }
}

constexpr const char* kErrPrefix = "{\"type\":\"ERROR\"";
constexpr size_t kErrPrefixLen = 15;

}  // namespace

extern "C" {

void* kwok_watch_open(int fd, const char* initial, int64_t n, int identity) {
  auto* r = new WatchReader();
  r->fd = fd;
  r->identity = identity != 0;
  if (initial && n > 0) r->in.assign(initial, (size_t)n);
  return r;
}

void kwok_watch_close(void* h) { delete static_cast<WatchReader*>(h); }

// Returns: >0 = number of lines written to out/out_off (off has n+1
// entries, lines are \n- and \r-stripped); 0 = timeout, nothing ready;
// -1 = end of stream (no more lines will ever come; a partial trailing
// line is dropped — the resume revision replays it); -2 = a single line
// exceeds out_cap, *need holds the required capacity. *err is set to 1
// when the LAST returned line matched the ERROR-event prefix (no further
// lines are consumed past it this call).
int64_t kwok_watch_read(void* h, int timeout_ms, char* out, int64_t out_cap,
                        int64_t* out_off, int64_t max_lines, int32_t* err,
                        int64_t* need) {
  auto* r = static_cast<WatchReader*>(h);
  *err = 0;
  *need = 0;
  int64_t n = 0;
  int64_t used = 0;
  out_off[0] = 0;
  for (;;) {
    dechunk(*r);
    // split body into lines
    while (n < max_lines) {
      size_t nl = r->body.find('\n', r->body_off);
      if (nl == std::string::npos) break;
      size_t start = r->body_off;
      size_t end = nl;
      if (end > start && r->body[end - 1] == '\r') end--;
      size_t len = end - start;
      if (len == 0) {  // blank keep-alive line
        r->body_off = nl + 1;
        continue;
      }
      if (used + (int64_t)len > out_cap) {
        if (n == 0) {
          *need = (int64_t)len;
          return -2;
        }
        goto done;  // deliver what fits; rest next call
      }
      bool is_err = len >= kErrPrefixLen &&
                    memcmp(r->body.data() + start, kErrPrefix,
                           kErrPrefixLen) == 0;
      memcpy(out + used, r->body.data() + start, len);
      used += len;
      n++;
      out_off[n] = used;
      r->body_off = nl + 1;
      if (is_err) {
        *err = 1;
        goto done;  // nothing past a stream error is consumed this call
      }
    }
    if (n > 0) goto done;
    if (r->eof) return -1;
    // nothing complete buffered: wait for the socket
    struct pollfd pfd{r->fd, POLLIN, 0};
    int pr = poll(&pfd, 1, timeout_ms);
    if (pr == 0) return 0;  // timeout
    if (pr < 0) {
      if (errno == EINTR) return 0;  // PEP-475: a signal is not a hangup
      r->eof = true;
      return -1;
    }
    char tmp[65536];
    ssize_t got = recv(r->fd, tmp, sizeof tmp, 0);
    if (got < 0 && errno == EINTR) continue;
    if (got <= 0) {
      r->eof = true;
      // fall through once more: the final dechunk may complete lines
      dechunk(*r);
      continue;
    }
    r->in.append(tmp, (size_t)got);
  }
done:
  if (r->body_off > (1u << 20) ||
      (r->body_off && r->body_off == r->body.size())) {
    r->body.erase(0, r->body_off);
    r->body_off = 0;
  }
  return n;
}

}  // extern "C"
