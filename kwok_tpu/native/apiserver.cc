// kwok-mock-apiserver: native in-memory kube-apiserver for the mock runtime.
//
// The Python HttpFakeApiserver (kwok_tpu/edge/mockserver.py) is the semantic
// source of truth; this binary speaks the same wire protocol at native
// speed so the lab apiserver is never the wall when benchmarking the
// engine's watch/patch edge (SURVEY.md §7 "Hard parts": the edge, not the
// math, is the bottleneck; the reference sidesteps it by being slow).
// kwokctl's mock runtime prefers this binary when a compiler is available
// and falls back to the Python shim otherwise; both serve:
//
//   GET    /healthz                      -> "ok"
//   GET    /snapshot                     -> whole-store dump (mock etcdctl)
//   POST   /restore                      -> replace store, close watches
//   GET    /api/v1[/namespaces/NS]/KIND              list (+watch=true)
//   GET    /api/v1[/namespaces/NS]/KIND/NAME         get
//   POST   /api/v1[/namespaces/NS]/KIND              create
//   PATCH  /api/v1[/namespaces/NS]/KIND/NAME[/status] strategic-merge status
//                                                     / merge-patch meta+spec
//   DELETE /api/v1[/namespaces/NS]/KIND/NAME         (graceful for pods)
//
// Concurrency model: thread-per-connection (connection counts are bounded:
// engine watches + patch pool + loaders), one store mutex. Each watch event
// is serialized ONCE and the bytes shared across all matching watchers.
// JSON numbers are kept as raw token text end-to-end so stored objects
// round-trip byte-exactly.
//
// Build: g++ -O2 -std=c++17 -pthread -o kwok-mock-apiserver apiserver.cc

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <chrono>
#include <thread>
#include <utility>
#include <vector>

// ---------------------------------------------------------------- JSON DOM

struct JVal;
using JObj = std::vector<std::pair<std::string, JVal>>;  // insertion order

struct JVal {
  enum Type : uint8_t { NUL, BOOL, NUM, STR, ARR, OBJ } type = NUL;
  bool b = false;
  std::string s;  // STR: decoded text; NUM: raw token text
  std::vector<JVal> arr;
  JObj obj;

  bool is_obj() const { return type == OBJ; }
  const JVal* find(const std::string& k) const {
    if (type != OBJ) return nullptr;
    for (const auto& kv : obj)
      if (kv.first == k) return &kv.second;
    return nullptr;
  }
  JVal* find(const std::string& k) {
    if (type != OBJ) return nullptr;
    for (auto& kv : obj)
      if (kv.first == k) return &kv.second;
    return nullptr;
  }
  JVal& set(const std::string& k, JVal v) {
    if (JVal* e = find(k)) {
      *e = std::move(v);
      return *e;
    }
    obj.emplace_back(k, std::move(v));
    return obj.back().second;
  }
  JVal& get_or_insert_obj(const std::string& k) {
    if (JVal* e = find(k)) {
      if (e->type != OBJ) *e = JVal{OBJ};
      return *e;
    }
    JVal v;
    v.type = OBJ;
    obj.emplace_back(k, std::move(v));
    return obj.back().second;
  }
  void erase(const std::string& k) {
    if (type != OBJ) return;
    for (auto it = obj.begin(); it != obj.end(); ++it)
      if (it->first == k) {
        obj.erase(it);
        return;
      }
  }
  static JVal str(std::string v) {
    JVal j;
    j.type = STR;
    j.s = std::move(v);
    return j;
  }
  static JVal num_raw(std::string v) {
    JVal j;
    j.type = NUM;
    j.s = std::move(v);
    return j;
  }
};

// --- parser (recursive descent; tolerant of whitespace; \uXXXX -> UTF-8)

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JParser(const std::string& text)
      : p(text.data()), end(text.data() + text.size()) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool lit(const char* s, size_t n) {
    if ((size_t)(end - p) < n || std::memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  JVal parse() {
    ws();
    JVal v = value();
    ws();
    if (p != end) ok = false;
    return v;
  }

  JVal value() {
    if (p >= end) {
      ok = false;
      return {};
    }
    switch (*p) {
      case '{':
        return object();
      case '[':
        return array();
      case '"': {
        JVal v;
        v.type = JVal::STR;
        v.s = string();
        return v;
      }
      case 't':
        if (lit("true", 4)) {
          JVal v;
          v.type = JVal::BOOL;
          v.b = true;
          return v;
        }
        break;
      case 'f':
        if (lit("false", 5)) {
          JVal v;
          v.type = JVal::BOOL;
          v.b = false;
          return v;
        }
        break;
      case 'n':
        if (lit("null", 4)) return {};
        break;
      default:
        if (*p == '-' || (*p >= '0' && *p <= '9')) return number();
    }
    ok = false;
    return {};
  }

  JVal number() {
    const char* s = p;
    if (p < end && *p == '-') p++;
    while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                       *p == 'E' || *p == '+' || *p == '-'))
      p++;
    JVal v;
    v.type = JVal::NUM;
    v.s.assign(s, p - s);
    return v;
  }

  std::string string() {
    std::string out;
    if (p >= end || *p != '"') {
      ok = false;
      return out;
    }
    p++;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        p++;
        if (p >= end) break;
        switch (*p) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned cp = hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[1] == '\\' &&
                p[2] == 'u') {
              p += 2;
              unsigned lo = hex4();
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, cp);
            break;
          }
          default: ok = false;
        }
        p++;
      } else {
        out += *p++;
      }
    }
    if (p < end) p++;  // closing quote
    else ok = false;
    return out;
  }

  unsigned hex4() {
    unsigned v = 0;
    for (int i = 0; i < 4 && p + 1 < end; i++) {
      p++;
      char c = *p;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else ok = false;
    }
    return v;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out += (char)cp;
    } else if (cp < 0x800) {
      out += (char)(0xC0 | (cp >> 6));
      out += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += (char)(0xE0 | (cp >> 12));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    } else {
      out += (char)(0xF0 | (cp >> 18));
      out += (char)(0x80 | ((cp >> 12) & 0x3F));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    }
  }

  JVal object() {
    JVal v;
    v.type = JVal::OBJ;
    p++;  // {
    ws();
    if (p < end && *p == '}') {
      p++;
      return v;
    }
    while (p < end) {
      ws();
      std::string k = string();
      ws();
      if (p >= end || *p != ':') {
        ok = false;
        return v;
      }
      p++;
      ws();
      v.obj.emplace_back(std::move(k), value());
      ws();
      if (p < end && *p == ',') {
        p++;
        continue;
      }
      break;
    }
    if (p < end && *p == '}') p++;
    else ok = false;
    return v;
  }

  JVal array() {
    JVal v;
    v.type = JVal::ARR;
    p++;  // [
    ws();
    if (p < end && *p == ']') {
      p++;
      return v;
    }
    while (p < end) {
      ws();
      v.arr.push_back(value());
      ws();
      if (p < end && *p == ',') {
        p++;
        continue;
      }
      break;
    }
    if (p < end && *p == ']') p++;
    else ok = false;
    return v;
  }
};

static void json_escape(std::string& out, const std::string& s) {
  static const char hex[] = "0123456789abcdef";
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          out += "\\u00";
          out += hex[c >> 4];
          out += hex[c & 15];
        } else {
          out += (char)c;
        }
    }
  }
}

static void serialize(const JVal& v, std::string& out) {
  switch (v.type) {
    case JVal::NUL: out += "null"; break;
    case JVal::BOOL: out += v.b ? "true" : "false"; break;
    case JVal::NUM: out += v.s; break;
    case JVal::STR:
      out += '"';
      json_escape(out, v.s);
      out += '"';
      break;
    case JVal::ARR: {
      out += '[';
      for (size_t i = 0; i < v.arr.size(); i++) {
        if (i) out += ',';
        serialize(v.arr[i], out);
      }
      out += ']';
      break;
    }
    case JVal::OBJ: {
      out += '{';
      for (size_t i = 0; i < v.obj.size(); i++) {
        if (i) out += ',';
        out += '"';
        json_escape(out, v.obj[i].first);
        out += "\":";
        serialize(v.obj[i].second, out);
      }
      out += '}';
      break;
    }
  }
}

static std::string dumps(const JVal& v) {
  std::string out;
  serialize(v, out);
  return out;
}

// ------------------------------------------------------------- selectors

// Label-selector grammar mirror of kwok_tpu/edge/selectors.py: `k=v`,
// `k==v`, `k!=v`, `k in (a,b)`, `k notin (a,b)`, `k`, `!k`, comma-joined.
struct LabelReq {
  enum Op { EQ, NE, IN, NOTIN, EXISTS, NOTEXISTS } op;
  std::string key;
  std::vector<std::string> values;

  bool matches(const JVal* labels) const {
    const JVal* v = labels ? labels->find(key) : nullptr;
    bool present = v != nullptr && v->type == JVal::STR;
    switch (op) {
      case EXISTS: return v != nullptr;
      case NOTEXISTS: return v == nullptr;
      case EQ:
      case IN: {
        if (!present) return false;
        for (const auto& x : values)
          if (x == v->s) return true;
        return false;
      }
      case NE:
      case NOTIN: {
        if (!present) return true;  // absent matches != / notin
        for (const auto& x : values)
          if (x == v->s) return false;
        return true;
      }
    }
    return false;
  }
};

static std::string strip(const std::string& s) {
  size_t a = s.find_first_not_of(" \t");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t");
  return s.substr(a, b - a + 1);
}

static std::vector<std::string> split_top_level(const std::string& s) {
  std::vector<std::string> parts;
  int depth = 0;
  std::string cur;
  for (char ch : s) {
    if (ch == '(') depth++;
    else if (ch == ')') depth--;
    if (ch == ',' && depth == 0) {
      std::string t = strip(cur);
      if (!t.empty()) parts.push_back(t);
      cur.clear();
    } else {
      cur += ch;
    }
  }
  std::string t = strip(cur);
  if (!t.empty()) parts.push_back(t);
  return parts;
}

struct LabelSel {
  std::vector<LabelReq> reqs;
  bool parsed = false;  // false => no selector (match everything)

  static LabelSel parse(const std::string& s) {
    LabelSel sel;
    std::string t = strip(s);
    if (t.empty()) return sel;
    sel.parsed = true;
    for (const std::string& part : split_top_level(t)) {
      LabelReq r;
      size_t sp = part.find(' ');
      // `key in (a,b)` / `key notin (a,b)`
      if (sp != std::string::npos) {
        std::string key = strip(part.substr(0, sp));
        std::string rest = strip(part.substr(sp));
        bool isin = rest.rfind("in", 0) == 0 && rest.find('(') != std::string::npos;
        bool isnot = rest.rfind("notin", 0) == 0;
        if ((isin || isnot) && key.find('=') == std::string::npos &&
            key.find('!') == std::string::npos) {
          size_t lp = rest.find('('), rp = rest.rfind(')');
          if (lp != std::string::npos && rp != std::string::npos && rp > lp) {
            r.key = key;
            r.op = isnot ? LabelReq::NOTIN : LabelReq::IN;
            std::string vals = rest.substr(lp + 1, rp - lp - 1);
            size_t pos = 0;
            while (pos <= vals.size()) {
              size_t c = vals.find(',', pos);
              std::string v =
                  strip(vals.substr(pos, c == std::string::npos ? c : c - pos));
              if (!v.empty()) r.values.push_back(v);
              if (c == std::string::npos) break;
              pos = c + 1;
            }
            sel.reqs.push_back(std::move(r));
            continue;
          }
        }
      }
      size_t ne = part.find("!=");
      size_t ee = part.find("==");
      size_t e = part.find('=');
      if (ne != std::string::npos) {
        r.key = strip(part.substr(0, ne));
        r.op = LabelReq::NE;
        r.values.push_back(strip(part.substr(ne + 2)));
      } else if (ee != std::string::npos) {
        r.key = strip(part.substr(0, ee));
        r.op = LabelReq::EQ;
        r.values.push_back(strip(part.substr(ee + 2)));
      } else if (e != std::string::npos) {
        r.key = strip(part.substr(0, e));
        r.op = LabelReq::EQ;
        r.values.push_back(strip(part.substr(e + 1)));
      } else if (!part.empty() && part[0] == '!') {
        r.key = strip(part.substr(1));
        r.op = LabelReq::NOTEXISTS;
      } else {
        r.key = part;
        r.op = LabelReq::EXISTS;
      }
      sel.reqs.push_back(std::move(r));
    }
    return sel;
  }

  bool matches(const JVal& obj) const {
    if (!parsed) return true;
    const JVal* meta = obj.find("metadata");
    const JVal* labels = meta ? meta->find("labels") : nullptr;
    for (const auto& r : reqs)
      if (!r.matches(labels)) return false;
    return true;
  }
};

// fieldSelector: comma-joined `path=value` / `path!=value` terms; missing
// fields stringify to "" (kwok_tpu/edge/kubeclient.py match_field_selector).
static std::string field_str(const JVal& obj, const std::string& path) {
  const JVal* cur = &obj;
  size_t pos = 0;
  while (pos <= path.size()) {
    size_t dot = path.find('.', pos);
    std::string part =
        path.substr(pos, dot == std::string::npos ? dot : dot - pos);
    if (cur->type != JVal::OBJ) return "";
    cur = cur->find(strip(part));
    if (!cur) return "";
    if (dot == std::string::npos) break;
    pos = dot + 1;
  }
  switch (cur->type) {
    case JVal::STR: return cur->s;
    case JVal::NUM: return cur->s;
    case JVal::BOOL: return cur->b ? "True" : "False";  // Python str(bool)
    default: return "";
  }
}

static bool match_field_selector(const JVal& obj, const std::string& sel) {
  if (sel.empty()) return true;
  size_t pos = 0;
  while (pos <= sel.size()) {
    size_t c = sel.find(',', pos);
    std::string term =
        strip(sel.substr(pos, c == std::string::npos ? c : c - pos));
    if (!term.empty()) {
      size_t ne = term.find("!=");
      if (ne != std::string::npos) {
        std::string path = term.substr(0, ne);
        std::string val = term.substr(ne + 2);
        if (field_str(obj, path) == val) return false;
      } else {
        size_t ee = term.find("==");
        size_t e = term.find('=');
        std::string path, val;
        if (ee != std::string::npos) {
          path = term.substr(0, ee);
          val = term.substr(ee + 2);
        } else if (e != std::string::npos) {
          path = term.substr(0, e);
          val = term.substr(e + 1);
        } else {
          goto next;
        }
        // mirror Python's path.rstrip("=") on the `=` split
        while (!path.empty() && path.back() == '=') path.pop_back();
        if (field_str(obj, path) != val) return false;
      }
    }
  next:
    if (c == std::string::npos) break;
    pos = c + 1;
  }
  return true;
}

// ------------------------------------------------------- strategic merge

// Mirrors kwok_tpu/edge/merge.py: object merge with null deletion; list
// merge by key `type` for fields `conditions`/`addresses`; everything else
// replaces atomically. `$patch: replace`/`$patch: delete` directives follow
// the real apiserver's strategicpatch for these shapes (merge.py docstring);
// unknown directive values are dropped tolerantly.
static bool merge_list_field(const std::string& field) {
  return field == "conditions" || field == "addresses";
}

static const JVal* patch_directive(const JVal& v) {
  const JVal* d = v.type == JVal::OBJ ? v.find("$patch") : nullptr;
  return (d && d->type == JVal::STR) ? d : nullptr;
}

// True when a patch subtree carries no $patch markers and no nulls — the
// common case, letting insertion skip the sanitizing rebuild.
static bool patch_clean(const JVal& v) {
  if (v.type == JVal::OBJ) {
    for (const auto& kv : v.obj)
      if (kv.first == "$patch" || kv.second.type == JVal::NUL ||
          !patch_clean(kv.second))
        return false;
    return true;
  }
  if (v.type == JVal::ARR) {
    for (const auto& e : v.arr)
      if (!patch_clean(e)) return false;
    return true;
  }
  return true;
}

// A patch subtree inserted where the original has no value: stored objects
// must never contain $patch markers or nulls (mirrors merge.py _sanitize /
// strategicpatch IgnoreUnmatchedNulls). Known divergence from upstream
// removeDirectives, shared by all three in-repo implementations (see the
// merge.py _sanitize docstring): a fresh-inserted $patch:delete map becomes
// {} and directive-carrying merge-list elements are dropped, where upstream
// merely strips the marker and keeps the content.
static JVal sanitize_patch(const JVal& v, const std::string& field) {
  if (patch_clean(v)) return v;
  if (v.type == JVal::OBJ) {
    const JVal* d = patch_directive(v);
    if (d && d->s == "delete") {
      JVal out;
      out.type = JVal::OBJ;
      return out;
    }
    JVal out;
    out.type = JVal::OBJ;
    for (const auto& kv : v.obj) {
      if (kv.first == "$patch" || kv.second.type == JVal::NUL) continue;
      out.obj.emplace_back(kv.first, sanitize_patch(kv.second, kv.first));
    }
    return out;
  }
  if (v.type == JVal::ARR && merge_list_field(field)) {
    JVal out;
    out.type = JVal::ARR;
    for (const auto& e : v.arr) {
      if (e.type == JVal::OBJ && e.find("$patch")) continue;
      out.arr.push_back(sanitize_patch(e, ""));
    }
    return out;
  }
  return v;  // scalars and atomic lists: opaque values, taken verbatim
}

static JVal merge_value(const JVal& orig, const JVal& patch,
                        const std::string& field) {
  if (patch.type == JVal::OBJ && orig.type == JVal::OBJ) {
    if (const JVal* d = patch_directive(patch)) {
      if (d->s == "replace") {
        JVal out;
        out.type = JVal::OBJ;
        for (const auto& kv : patch.obj) {
          if (kv.first == "$patch" || kv.second.type == JVal::NUL) continue;
          out.obj.emplace_back(kv.first, sanitize_patch(kv.second, kv.first));
        }
        return out;
      }
      if (d->s == "delete") {
        JVal out;
        out.type = JVal::OBJ;
        return out;
      }
    }
    JVal out = orig;
    for (const auto& kv : patch.obj) {
      if (kv.first == "$patch") continue;  // unknown directive: dropped
      if (kv.second.type == JVal::NUL) {
        out.erase(kv.first);
      } else if (JVal* cur = out.find(kv.first)) {
        *cur = merge_value(*cur, kv.second, kv.first);
      } else {
        out.obj.emplace_back(kv.first, sanitize_patch(kv.second, kv.first));
      }
    }
    return out;
  }
  if (patch.type == JVal::ARR && orig.type == JVal::ARR &&
      merge_list_field(field)) {
    // a `$patch: replace` element -> the patch's non-directive elements
    // replace the list wholesale
    for (const auto& item : patch.arr) {
      const JVal* d = patch_directive(item);
      if (d && d->s == "replace") {
        JVal out;
        out.type = JVal::ARR;
        for (const auto& it : patch.arr)
          if (!(it.type == JVal::OBJ && it.find("$patch")))
            out.arr.push_back(sanitize_patch(it, ""));
        return out;
      }
    }
    // strategicpatch applies every $patch:delete to the ORIGINAL before
    // merging any non-directive element, so a delete never removes an
    // element the same patch adds
    std::vector<std::string> deleted;
    for (const auto& item : patch.arr) {
      const JVal* d = patch_directive(item);
      const JVal* ik = item.type == JVal::OBJ ? item.find("type") : nullptr;
      if (d && d->s == "delete" && ik && ik->type == JVal::STR)
        deleted.push_back(ik->s);
    }
    JVal out = orig;
    if (!deleted.empty()) {
      auto& v = out.arr;
      v.erase(std::remove_if(v.begin(), v.end(),
                             [&](const JVal& e) {
                               const JVal* ek = e.type == JVal::OBJ
                                                    ? e.find("type")
                                                    : nullptr;
                               return ek && ek->type == JVal::STR &&
                                      std::find(deleted.begin(), deleted.end(),
                                                ek->s) != deleted.end();
                             }),
              v.end());
    }
    for (const auto& item : patch.arr) {
      if (item.type == JVal::OBJ && item.find("$patch")) continue;
      const JVal* ik = item.type == JVal::OBJ ? item.find("type") : nullptr;
      bool key_is_str = ik && ik->type == JVal::STR;
      bool merged = false;
      if (key_is_str) {
        for (auto& existing : out.arr) {
          const JVal* ek =
              existing.type == JVal::OBJ ? existing.find("type") : nullptr;
          if (ek && ek->type == JVal::STR && ek->s == ik->s) {
            existing = merge_value(existing, item, "");
            merged = true;
            break;
          }
        }
      }
      if (!merged) out.arr.push_back(sanitize_patch(item, ""));
    }
    return out;
  }
  // type-mismatch / scalar / atomic-list replacement: sanitized like
  // missing-key insertions
  return sanitize_patch(patch, field);
}

// ----------------------------------------------------------------- store

static std::string now_rfc3339() {
  time_t t = time(nullptr);
  struct tm tm_;
  gmtime_r(&t, &tm_);
  char buf[32];
  strftime(buf, sizeof buf, "%Y-%m-%dT%H:%M:%SZ", &tm_);
  return buf;
}

using Key = std::pair<std::string, std::string>;  // (namespace-or-"", name)

// Copy-on-write store entry: immutable once published, serialized at
// publish time. Readers (LIST/GET/snapshot at 1M objects) snapshot the
// shared_ptrs under the store mutex and do ALL matching/serialization
// outside it — a full-population LIST must never starve writers (measured:
// serializing 1M pods under the lock stalled every patch for seconds and
// timed out the engine's pump).
struct Entry {
  JVal obj;
  std::string bytes;
};
using EntryPtr = std::shared_ptr<const Entry>;

static EntryPtr publish(JVal obj) {
  auto e = std::make_shared<Entry>();
  e->obj = std::move(obj);
  e->bytes = dumps(e->obj);
  return e;
}

// bounded per-watcher send buffer: a consumer that stops reading has its
// watch TERMINATED (kwok_watch_terminations_total{reason="slow"}, the
// watch cache's slow-consumer termination) instead of pinning unbounded
// memory; the client re-lists/resumes (410-class recovery). Mirrors
// mockserver.py WATCH_BACKLOG; same env override; <= 0 disables the cap.
static long watch_backlog() {
  static const long bl = [] {
    const char* v = getenv("KWOK_TPU_WATCH_BACKLOG");
    return v && *v ? atol(v) : 16384L;
  }();
  return bl;
}

// kwok_watch_terminations_total{reason=}: slow-consumer closes happen in
// Watch::push (no App pointer there), timeoutSeconds expiries in the
// writer loop; one store per process, so file-scope atomics suffice.
static std::atomic<long> g_watch_term_slow{0};
static std::atomic<long> g_watch_term_deadline{0};

// ---------------------------------------------------------- phase timing
// (ISSUE 11) Per-request phase attribution, parity-pinned with
// kwok_tpu/telemetry/apiserver_metrics.py: family names, HELP text,
// bucket labels and the full phase/verb sample matrix are byte-identical
// across the two servers (tests/test_native_apiserver.py masks only the
// values). Clock stamps are gated by KWOK_TPU_APISERVER_TIMING (default
// on; "0" makes every request pay exactly one cached-bool branch); the
// fanout-push counter and the backlog peak watermark stay on — they are
// single relaxed atomics per queued event and the fleet gate's
// bounded-buffer proof must not depend on the timing knob.

static bool timing_enabled() {
  static const bool on = [] {
    const char* v = getenv("KWOK_TPU_APISERVER_TIMING");
    return !(v && v[0] == '0' && v[1] == '\0');
  }();
  return on;
}

static inline uint64_t now_ns() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

static double wall_unix_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// fixed bucket ladder (seconds, here as ns): telemetry.apiserver_metrics
// TIMING_BUCKETS — the `le` strings below are the canonical label bytes
static const int N_TBUCKETS = 17;
static const uint64_t TBUCKET_NS[N_TBUCKETS] = {
    5000ull,      10000ull,     25000ull,     50000ull,     100000ull,
    250000ull,    500000ull,    1000000ull,   2500000ull,   5000000ull,
    10000000ull,  25000000ull,  50000000ull,  100000000ull, 250000000ull,
    500000000ull, 1000000000ull};
static const char* TBUCKET_LE[N_TBUCKETS] = {
    "5e-06", "1e-05", "2.5e-05", "5e-05", "0.0001", "0.00025", "0.0005",
    "0.001", "0.0025", "0.005",  "0.01",  "0.025",  "0.05",    "0.1",
    "0.25",  "0.5",   "1"};

struct PhaseHist {
  std::atomic<uint64_t> buckets[N_TBUCKETS + 1] = {};
  std::atomic<uint64_t> sum_ns{0};
  std::atomic<uint64_t> count{0};
  void observe_ns(uint64_t ns) {
    int i = 0;
    while (i < N_TBUCKETS && ns > TBUCKET_NS[i]) i++;  // le inclusive
    buckets[i].fetch_add(1, std::memory_order_relaxed);
    sum_ns.fetch_add(ns, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
  }
};

enum {
  PH_READ_HEADERS = 0,
  PH_READ_BODY,
  PH_PARSE,
  PH_COMMIT,
  PH_ENCODE,
  PH_FANOUT,
  N_PHASES,
};
static const char* PHASE_NAMES[N_PHASES] = {
    "read_headers", "read_body", "parse", "commit", "encode", "fanout"};
static const int N_VERBS = 6;
static const char* VERB_NAMES[N_VERBS] = {"get",   "list",   "create",
                                          "patch", "delete", "other"};
static PhaseHist g_phase_hist[N_PHASES];
static PhaseHist g_verb_hist[N_VERBS];
static std::atomic<long> g_fanout_pushes{0};
static std::atomic<long> g_backlog_peak{0};

static void peak_update(long depth) {
  long prev = g_backlog_peak.load(std::memory_order_relaxed);
  while (depth > prev &&
         !g_backlog_peak.compare_exchange_weak(prev, depth)) {
  }
}

// Per-request phase accumulator: boundary stamps shared between adjacent
// phases (mark() is one clock read), so a timed unary request costs a
// handful of clock reads total; disabled => `on` stays false everywhere.
struct PhaseTimer {
  bool on = false;
  // set by handlers only when the body parse SUCCEEDED — a malformed
  // body contributes no parse sample, mirroring the Python mock (whose
  // _BadBody raise precedes its parse stamp)
  bool parsed = false;
  uint64_t last = 0;
  double us[N_PHASES] = {0, 0, 0, 0, 0, 0};
  void mark(int phase) {
    if (!on) return;
    uint64_t now = now_ns();
    us[phase] += (double)(now - last) / 1000.0;
    last = now;
  }
};

// flight recorder: a bounded ring of recent request records, dumped via
// GET /debug/flight (schema shared with the Python mock and validated by
// kwok_tpu/telemetry/timeline.check_flight)
static const size_t FLIGHT_CAPACITY = 1024;
struct FlightRec {
  std::string method, path, band;
  int status = 0;
  double ts_unix = 0;
  double total_us = 0;
  double phases_us[N_PHASES] = {0, 0, 0, 0, 0, 0};
};
static std::mutex g_flight_mu;  // leaf: nothing acquired under it
static std::deque<FlightRec> g_flight;
static long g_flight_captured = 0;

static void flight_record(FlightRec rec) {
  std::lock_guard<std::mutex> lk(g_flight_mu);
  g_flight_captured++;
  if (g_flight.size() >= FLIGHT_CAPACITY) g_flight.pop_front();
  g_flight.push_back(std::move(rec));
}

// kwok_watch_cursor_lag_events (ISSUE 16): final ring-cursor lag per
// watch close — the census histogram the C10k reactor rewrite is graded
// against. Bucket bounds/label bytes mirror telemetry/apiserver_metrics
// LAG_EVENT_BUCKETS; observed under the store's ring_mu (relaxed atomics
// so the /metrics render needs no lock).
static const int N_LBUCKETS = 13;
static const long LBUCKET_EV[N_LBUCKETS] = {1,   2,   4,   8,    16,   32,
                                            64,  128, 256, 512,  1024, 2048,
                                            4096};
static const char* LBUCKET_LE[N_LBUCKETS] = {
    "1",   "2",   "4",   "8",    "16",   "32",  "64",
    "128", "256", "512", "1024", "2048", "4096"};
static std::atomic<uint64_t> g_lag_buckets[N_LBUCKETS + 1] = {};
static std::atomic<uint64_t> g_lag_sum{0};
static std::atomic<uint64_t> g_lag_count{0};

static void lag_observe(long events) {
  if (events < 0) events = 0;
  int i = 0;
  while (i < N_LBUCKETS && events > LBUCKET_EV[i]) i++;  // le inclusive
  g_lag_buckets[i].fetch_add(1, std::memory_order_relaxed);
  g_lag_sum.fetch_add((uint64_t)events, std::memory_order_relaxed);
  g_lag_count.fetch_add(1, std::memory_order_relaxed);
}

static std::string flight_dump_json() {
  std::string out = "{\"server\":\"native\",\"timing_enabled\":";
  out += timing_enabled() ? "true" : "false";
  out += ",\"ring_capacity\":" + std::to_string(FLIGHT_CAPACITY);
  std::lock_guard<std::mutex> lk(g_flight_mu);
  out += ",\"captured\":" + std::to_string(g_flight_captured);
  out += ",\"records\":[";
  char num[64];
  bool first = true;
  for (const auto& r : g_flight) {
    if (!first) out += ',';
    first = false;
    out += "{\"method\":\"";
    json_escape(out, r.method);
    out += "\",\"path\":\"";
    json_escape(out, r.path);
    out += "\",\"status\":" + std::to_string(r.status);
    out += ",\"band\":\"";
    json_escape(out, r.band);
    out += "\"";
    snprintf(num, sizeof num, ",\"ts_unix\":%.6f", r.ts_unix);
    out += num;
    snprintf(num, sizeof num, ",\"total_us\":%.3f", r.total_us);
    out += num;
    out += ",\"phases_us\":{";
    for (int p = 0; p < N_PHASES; p++) {
      if (p) out += ',';
      out += "\"";
      out += PHASE_NAMES[p];
      snprintf(num, sizeof num, "\":%.3f", r.phases_us[p]);
      out += num;
    }
    out += "}}";
  }
  out += "]}";
  return out;
}

// A watch is a CURSOR into the store's serialize-once broadcast ring
// (ISSUE 13): the store encodes each event exactly once into the shared
// ring; every watch stream thread reads forward from its own cursor and
// filters on its own time (kind / selectors / bookmark opt-in), so the
// per-watcher encode+push loop left the commit path entirely. A watch
// whose cursor falls more than watch_backlog() events behind the ring
// head is closed terminated_slow — PR 8's bounded-backlog drop/close
// semantics folded into ring-cursor lag. All fields below `replay` are
// guarded by the store's ring/clock mutex (Store::mu).
struct Watch {
  int kind;  // 0 nodes, 1 pods
  std::string field_sel;
  LabelSel label_sel;
  // opted into periodic BOOKMARK events (allowWatchBookmarks=true)
  bool bookmarks = false;
  // resume replay (watch-cache gap): exempt from the lag cap — the gap
  // is bounded by rv_window() already, and capping it would terminate
  // every resume whose gap exceeds the backlog (a loop). Filled before
  // the watch is registered, so no reader races it.
  std::vector<std::shared_ptr<const std::string>> replay;
  // guarded by Store::mu from here on
  uint64_t cursor = 0;  // next ring sequence this stream will read
  // a graceful close still delivers events sequenced before the stop
  // point; a slow termination drops the backlog (cursor jumps to head)
  uint64_t stop_seq = UINT64_MAX;
  bool closed = false;
  // set when the server closed this watch because its ring-cursor lag
  // exceeded the cap (the writer distinguishes it from a shutdown close)
  bool terminated_slow = false;
  // wall stamp of registration — GET /debug/watchers age_s
  double created_unix = 0;
  // live replay-backlog size for the census: the replay vector itself is
  // drained by the stream thread OUTSIDE the ring lock, so the census
  // reads this atomic instead of racing the vector
  std::atomic<long> replay_pending{0};
};

// core/v1 kinds plus rbac.authorization.k8s.io/v1 (served with bootstrap
// policy under --authorization; mirrors mockserver.py KINDS)
static const int NKINDS = 7;
// order matters: pods must stay index 1 (graceful-delete special case);
// indexes 2-5 are the rbac group, everything else is core/v1
static const char* KIND_NAMES[NKINDS] = {
    "nodes",        "pods",         "roles",    "rolebindings",
    "clusterroles", "clusterrolebindings",      "events",
};
static int kind_index(const std::string& kind) {
  for (int i = 0; i < NKINDS; i++)
    if (kind == KIND_NAMES[i]) return i;
  return -1;
}

// the real apiserver expires events on a ~1h etcd lease (--event-ttl,
// re-leased on every write); the mock bounds the events store by count
// instead — the least-recently-WRITTEN event (smallest resourceVersion) is
// evicted on insert — so long soaks with a real scheduler can't grow it
// without bound. Mirrors mockserver.py EVENTS_CAP; same env override;
// cap <= 0 means unbounded.
static int events_cap() {
  static const int cap = [] {
    const char* v = getenv("KWOK_TPU_EVENTS_CAP");
    return v && *v ? atoi(v) : 4096;
  }();
  return cap;
}

// watch-cache window: recent events retained for resourceVersion-resumed
// watches. Resuming below the window gets the real apiserver's 410 Gone
// ("too old resource version", etcd compaction semantics); <= 0 disables
// the cache so every resume expires. Mirrors mockserver.py RV_WINDOW.
static int rv_window() {
  static const int w = [] {
    const char* v = getenv("KWOK_TPU_RV_WINDOW");
    return v && *v ? atoi(v) : 4096;
  }();
  return w;
}

// watch-cache entry: ring position is the store clock at emit time (NOT
// the object's own rv — events-cap evictions re-emit old objects and the
// replay filter needs monotonic positions)
struct Hist {
  int64_t rv;
  int kind;
  std::string type;
  EntryPtr e;
};

// url-safe base64 for the opaque list continue token (the real
// apiserver's continue is base64 too; raw NULs don't survive shells/JSON)
static const char B64URL[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";

static std::string b64url_encode(const std::string& in) {
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 3 <= in.size()) {
    uint32_t v = (uint8_t)in[i] << 16 | (uint8_t)in[i + 1] << 8 |
                 (uint8_t)in[i + 2];
    out += B64URL[v >> 18];
    out += B64URL[(v >> 12) & 63];
    out += B64URL[(v >> 6) & 63];
    out += B64URL[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = (uint8_t)in[i] << 16;
    out += B64URL[v >> 18];
    out += B64URL[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (uint8_t)in[i] << 16 | (uint8_t)in[i + 1] << 8;
    out += B64URL[v >> 18];
    out += B64URL[(v >> 12) & 63];
    out += B64URL[(v >> 6) & 63];
    out += '=';
  }
  return out;
}

static bool b64url_decode(const std::string& in, std::string& out) {
  static int8_t rev[256];
  static bool init = [] {
    for (int i = 0; i < 256; i++) rev[i] = -1;
    for (int i = 0; i < 64; i++) rev[(uint8_t)B64URL[i]] = (int8_t)i;
    return true;
  }();
  (void)init;
  out.clear();
  uint32_t acc = 0;
  int bits = 0;
  for (char c : in) {
    if (c == '=') break;
    int8_t v = rev[(uint8_t)c];
    if (v < 0) return false;
    acc = acc << 6 | (uint32_t)v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += (char)((acc >> bits) & 0xff);
    }
  }
  return true;
}

// Undo record: the entry's state BEFORE the event at `rv` (nullptr =
// absent). Bounded by the same window as the watch cache, it lets a
// paginated LIST reconstruct the store as of a continue token's revision
// — the consistent snapshot the real apiserver reads from etcd MVCC.
struct Undo {
  int64_t rv;
  int kind;
  Key key;
  EntryPtr prev;
};

// coordination.k8s.io/v1 Lease record (ISSUE 12): the leadership plane's
// minimal dialect, mirrored byte-for-byte with mockserver.py's lease_*
// methods (parity twins in tests/test_native_apiserver.py). Wall epochs
// are kept alongside the rendered RFC3339 stamps so expiry arithmetic
// never re-parses a timestamp; the SERVER clock is the one authority.
// Leases live outside the watch/snapshot machinery by design: leadership
// is polled, never watched, and a restored store must not resurrect an
// old holder.
struct LeaseRec {
  std::string holder;
  long duration = 0;          // leaseDurationSeconds
  double acquire = 0, renew = 0;  // wall epochs (server clock)
  long transitions = 0;       // leaseTransitions
  std::string created, uid;
  int64_t rv = 0;
  std::string acquire_str, renew_str;
};

static std::string lease_render(const std::string& ns,
                                const std::string& name,
                                const LeaseRec& L) {
  std::string out =
      "{\"kind\":\"Lease\",\"apiVersion\":\"coordination.k8s.io/v1\","
      "\"metadata\":{\"name\":\"";
  json_escape(out, name);
  out += "\",\"namespace\":\"";
  json_escape(out, ns);
  out += "\",\"creationTimestamp\":\"" + L.created + "\",\"uid\":\"" +
         L.uid + "\",\"resourceVersion\":\"" + std::to_string(L.rv) +
         "\"},\"spec\":{\"holderIdentity\":\"";
  json_escape(out, L.holder);
  out += "\",\"leaseDurationSeconds\":" + std::to_string(L.duration) +
         ",\"acquireTime\":\"" + L.acquire_str + "\",\"renewTime\":\"" +
         L.renew_str + "\",\"leaseTransitions\":" +
         std::to_string(L.transitions) + "}}";
  return out;
}

// (holderIdentity, leaseDurationSeconds) from a request body's spec,
// tolerantly — a garbled duration reads as 0 (Python int() parity on the
// shapes our clients send).
static void lease_spec_fields(const JVal& body, std::string& holder,
                              long& duration) {
  holder.clear();
  duration = 0;
  const JVal* spec = body.is_obj() ? body.find("spec") : nullptr;
  if (!spec || !spec->is_obj()) return;
  const JVal* h = spec->find("holderIdentity");
  if (h && h->type == JVal::STR) holder = h->s;
  const JVal* d = spec->find("leaseDurationSeconds");
  if (d && (d->type == JVal::NUM || d->type == JVal::STR))
    duration = atol(d->s.c_str());
}

// server-clock expiry: vacant (no holder) counts as expired; otherwise a
// lease expires once renewTime + duration has passed (duration <= 0 =
// instantly reacquirable). Mirrors mockserver.FakeKube._lease_expired.
static bool lease_expired(const LeaseRec& L, double now) {
  if (L.holder.empty()) return true;
  return now >= L.renew + (double)(L.duration > 0 ? L.duration : 0);
}

// One (kind, namespace) store partition (ISSUE 13): its own mutex + map,
// so concurrent writers to different shards stop serializing on one
// index. Shard mutexes never nest with each other; the only nesting is
// shard -> Store::mu (the ring/clock lock) inside a commit. Cross-shard
// reads (LIST/snapshot) walk shards sequentially and reconcile through
// the undo log.
struct Shard {
  std::mutex smu;
  std::map<std::string, EntryPtr> objs;  // name -> published entry
};
using ShardPtr = std::shared_ptr<Shard>;

// One broadcast-ring entry: the event line is encoded exactly once and
// shared by every watcher whose cursor passes it; `e` is kept for the
// watcher-side selector match (immutable entry, no copies).
struct RingEv {
  int kind;
  bool bookmark;
  EntryPtr e;  // null for bookmarks
  std::shared_ptr<const std::string> line;
};

struct Store {
  // clock lock: revision allocation, watch cache (history), undo log,
  // per-kind counts + phase index. Acquired UNDER a shard's smu inside
  // commits (shard -> mu), never the other way around.
  std::mutex mu;
  // broadcast-ring lock: the ring itself, the watch registry and every
  // cursor. Acquired UNDER mu inside commits (shard -> mu -> ring_mu)
  // and ALONE by watcher threads — so a thousand watchers draining the
  // ring never contend with the clock lock a commit is serializing on.
  std::mutex ring_mu;
  std::condition_variable ring_cv;  // paired with ring_mu
  // shard registry (ns -> shard per kind); shards_mu guards creation
  // only and is never held together with any other lock
  std::mutex shards_mu;
  std::map<std::string, ShardPtr> shards[NKINDS];
  // coordination.k8s.io/v1 (ISSUE 12): leases + fencing live under their
  // own lease_mu, held ACROSS a fenced write's whole mutation (lease ->
  // shard -> mu) so a takeover PATCH can never interleave between the
  // fence check and the commit (the PR 12 contract, sharded edition)
  std::mutex lease_mu;
  std::map<Key, LeaseRec> leases;
  int64_t rv = 0;
  // watch registry + live count per kind: under ring_mu
  std::vector<std::shared_ptr<Watch>> watches;
  long kind_watchers[NKINDS] = {};
  // everything at or below compacted_rv is gone from history: resumes
  // below it answer 410, expired continue tokens too
  std::deque<Hist> history;
  std::deque<Undo> undo;
  int64_t compacted_rv = 0;
  // incremental status.phase counts per kind: lets a limit=1 progress
  // poll (fieldSelector=status.phase=X) report remainingItemCount without
  // the O(store) post-cut scan — at 50k pods a rig polling every 200 ms
  // was a measurable apiserver CPU term. Kept under mu (with rv) so the
  // count a LIST reads is consistent with its list revision.
  std::map<std::string, long> phase_idx[NKINDS];
  long obj_count[NKINDS] = {};  // per-kind population, under mu
  // the serialize-once broadcast ring (under ring_mu): base =
  // ring_next - ring.size(); trimmed to the slowest live cursor,
  // bounded by watch_backlog()
  std::deque<RingEv> ring;
  uint64_t ring_next = 0;
  uint64_t ring_min = 0;  // lazily-recomputed min live cursor estimate
  long encode_total = 0;  // kwok_watch_encode_total: one per ring append

  ShardPtr shard_of(int kind, const std::string& ns, bool create = true) {
    std::lock_guard<std::mutex> lk(shards_mu);
    auto it = shards[kind].find(ns);
    if (it != shards[kind].end()) return it->second;
    if (!create) return nullptr;
    auto sh = std::make_shared<Shard>();
    shards[kind][ns] = sh;
    return sh;
  }

  // (ns, shard) pairs in namespace order — concatenating their sorted
  // names yields the kind's global (ns, name) key order
  std::vector<std::pair<std::string, ShardPtr>> kind_shards(int kind) {
    std::lock_guard<std::mutex> lk(shards_mu);
    return {shards[kind].begin(), shards[kind].end()};
  }

  // caller holds mu; from/to are the entry leaving/entering the store
  void idx_adjust(int kind, const EntryPtr& from, const EntryPtr& to) {
    if (from) {
      std::string p = field_str(from->obj, "status.phase");
      auto it = phase_idx[kind].find(p);
      if (it != phase_idx[kind].end() && --it->second <= 0)
        phase_idx[kind].erase(it);
    }
    if (to) phase_idx[kind][field_str(to->obj, "status.phase")]++;
  }

  // caller holds ring_mu: close one watch (graceful or slow). A slow
  // termination drops the backlog (cursor jumps to head — 410-class
  // recovery); a graceful stop still delivers events queued before the
  // stop point. Wake-ups are the caller's job (ring_cv.notify_all after
  // the mu hold, or batched per commit).
  void close_watch_locked(const std::shared_ptr<Watch>& w, bool slow) {
    if (w->closed) return;
    w->closed = true;
    // census: the stream's FINAL lag, observed before any cursor jump (a
    // slow close records the overflow that killed it, a graceful close
    // the tail it still had to drain) — mirrors mockserver.py
    lag_observe((long)(ring_next - w->cursor));
    kind_watchers[w->kind]--;
    if (slow) {
      w->terminated_slow = true;
      w->cursor = ring_next;
      w->stop_seq = w->cursor;
      g_watch_term_slow.fetch_add(1);
    } else {
      w->stop_seq = ring_next;
    }
  }

  // caller holds ring_mu: trim consumed ring entries and enforce the cap.
  // Entries every live watcher consumed are dropped; once the ring
  // outgrows watch_backlog() the lagging watchers (cursor more than the
  // cap behind) are slow-closed and their backlog reclaimed. The peak
  // watermark records the deepest retained lag, clamped to the cap on a
  // termination, so fleet-check's gate (peak <= cap) keeps its meaning.
  void ring_trim_locked() {
    long cap = watch_backlog();
    while (!ring.empty()) {
      uint64_t base = ring_next - ring.size();
      if (ring_min <= base) {
        uint64_t m = ring_next;
        for (const auto& w : watches)
          if (!w->closed && w->cursor < m) m = w->cursor;
        ring_min = m;
      }
      if (ring_min > base) {
        ring.pop_front();
        continue;
      }
      if (cap > 0 && (long)ring.size() > cap) {
        bool lagged = false;
        for (const auto& w : watches)
          if (!w->closed && (long)(ring_next - w->cursor) > cap) {
            close_watch_locked(w, /*slow=*/true);
            lagged = true;
          }
        ring_min = 0;
        peak_update(cap);
        if (!lagged) break;  // safety: nobody to blame, stop trimming
        continue;
      }
      peak_update((long)ring.size());
      break;
    }
  }

  // caller holds the owning shard's smu (same-key writes stay totally
  // ordered) AND mu: allocate the revision, stamp it, serialize ONCE,
  // record watch cache + undo + counts, append the broadcast ring.
  // Returns the published entry; the caller installs it in the shard
  // map (or erased it already, for DELETED). `fanout_us` (timing on)
  // accumulates the one encode+append — the serialize-once cost the
  // old per-watcher loop paid per watcher.
  EntryPtr commit_locked(int kind, const char* type, JVal obj,
                         const Key& key, EntryPtr prev, double* fanout_us,
                         const Shard* owner, bool stamp_uid = false) {
    rv++;
    JVal& meta = obj.get_or_insert_obj("metadata");
    if (stamp_uid && !meta.find("uid"))
      meta.set("uid", JVal::str("uid-" + std::to_string(rv)));
    meta.set("resourceVersion", JVal::str(std::to_string(rv)));
    EntryPtr e = publish(std::move(obj));
    if (owner) {
      // a restore may have swapped the shard registry while this write
      // held its (now orphaned) shard. The client sees what the old
      // one-lock store gave — committed, then wiped by the restore —
      // so answer with the published entry but record NOTHING: no
      // counts (the restore reset them), no watch-cache/undo entry
      // (compacted), no ring event (watchers were closed); a ghost
      // event here is the silent divergence the drift auditor hunts.
      std::lock_guard<std::mutex> sg(shards_mu);
      auto sit = shards[kind].find(key.first);
      if (sit == shards[kind].end() || sit->second.get() != owner)
        return e;
    }
    bool deleted = strcmp(type, "DELETED") == 0;
    idx_adjust(kind, prev, deleted ? nullptr : e);
    if (!prev && !deleted) obj_count[kind]++;
    if (deleted) obj_count[kind]--;
    if (rv_window() > 0) {
      history.push_back({rv, kind, type, e});
      undo.push_back({rv, kind, key, std::move(prev)});
      while ((int)history.size() > rv_window()) {
        compacted_rv = std::max(compacted_rv, history.front().rv);
        history.pop_front();
      }
      while (!undo.empty() && undo.front().rv <= compacted_rv)
        undo.pop_front();
    }
    {
      // fanout (ISSUE 13): ONE encode + ring append per event no matter
      // how many watchers consume it. The push counter counts the
      // deliveries the shared bytes fan out to (events x live watchers
      // of the kind), so fanout_sum / fanout_total is the AMORTIZED
      // per-watcher cost; always on, clocks gated. ring_mu nests under
      // mu here (shard -> mu -> ring_mu) and is the ONLY lock watcher
      // threads ever take — their drains never stall the clock lock.
      uint64_t f0 = fanout_us ? now_ns() : 0;
      std::lock_guard<std::mutex> rl(ring_mu);
      if (kind_watchers[kind] > 0) {
        ring.push_back({kind, false, e, event_line(type, e)});
        ring_next++;
        encode_total++;
        g_fanout_pushes.fetch_add(kind_watchers[kind],
                                  std::memory_order_relaxed);
        ring_trim_locked();
        if (fanout_us) *fanout_us += (double)(now_ns() - f0) / 1000.0;
      }
    }
    return e;
  }

  static std::shared_ptr<const std::string> event_line(const char* type,
                                                       const EntryPtr& e) {
    std::string ev = "{\"type\":\"";
    ev += type;
    ev += "\",\"object\":";
    ev += e->bytes;
    ev += "}\n";
    return std::make_shared<const std::string>(std::move(ev));
  }

  // One BOOKMARK ring event (current store revision) per kind with
  // opted-in live watches — the watch cache's periodic rv-advance for
  // quiet watchers, encoded once per kind no matter the cohort size.
  // Object carries ONLY kind/apiVersion/metadata.resourceVersion, like
  // the real apiserver's (mirrors mockserver.py emit_bookmarks).
  int emit_bookmarks() {
    // object kind names + groups by KIND_NAMES index
    static const char* OBJ_KINDS[NKINDS] = {
        "Node",        "Pod",         "Role",    "RoleBinding",
        "ClusterRole", "ClusterRoleBinding",     "Event",
    };
    int sent = 0;
    {
      std::lock_guard<std::mutex> lk(mu);
      std::string rvs = std::to_string(rv);
      std::lock_guard<std::mutex> rl(ring_mu);
      long opted[NKINDS] = {};
      for (const auto& w : watches) {
        if (w->closed || !w->bookmarks) continue;
        opted[w->kind]++;
        sent++;
      }
      for (int k = 0; k < NKINDS; k++) {
        if (!opted[k]) continue;
        bool rbac = k >= 2 && k <= 5;
        std::string ev = "{\"type\":\"BOOKMARK\",\"object\":{\"kind\":\"";
        ev += OBJ_KINDS[k];
        ev += rbac ? "\",\"apiVersion\":\"rbac.authorization.k8s.io/v1\""
                   : "\",\"apiVersion\":\"v1\"";
        ev += ",\"metadata\":{\"resourceVersion\":\"" + rvs + "\"}}}\n";
        ring.push_back({k, true, nullptr,
                        std::make_shared<const std::string>(std::move(ev))});
        ring_next++;
        encode_total++;
      }
      if (sent) ring_trim_locked();
    }
    if (sent) ring_cv.notify_all();
    return sent;
  }

  static Key obj_key(const JVal& obj) {
    const JVal* meta = obj.find("metadata");
    const JVal* ns = meta ? meta->find("namespace") : nullptr;
    const JVal* name = meta ? meta->find("name") : nullptr;
    return {ns && ns->type == JVal::STR ? ns->s : "",
            name && name->type == JVal::STR ? name->s : ""};
  }
};

// ----------------------------------------------------------- HTTP server

struct Request {
  std::string method;
  std::string path;     // without query
  std::string query;    // raw query string
  std::string body;
  std::string auth;     // Authorization header (bearer-token authn)
  // X-Kwok-Lease-Holder: the fencing claim ("ns/name/holder") a mutating
  // request rides under; empty = unfenced (zero cost). Mirrors
  // mockserver.py FENCING_HEADER.
  std::string lease_holder;
  bool close = false;   // Connection: close
  // body handling is split from header parsing so max-inflight admission
  // can hold a band slot ACROSS the body read (a request is in flight
  // from its headers on, like the real apiserver's filter chain) and a
  // rejected request can still drain its body to keep the keep-alive
  // pipeline parseable
  size_t content_len = 0;
  bool body_read = false;
  // phase-timing boundary stamps (0 = timing off): first request bytes,
  // headers parsed, body consumed
  uint64_t t_start = 0;
  uint64_t t_hdr = 0;
  uint64_t t_body = 0;
};

static bool send_all(int fd, const char* data, size_t n) {
  while (n > 0) {
    ssize_t w = send(fd, data, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    data += w;
    n -= (size_t)w;
  }
  return true;
}

// Per-connection buffered IO. `in` carries pipelined request bytes behind
// a consumed-prefix offset (erasing the prefix per request is O(buffered)
// — quadratic under the pump's deep pipelines); `out` accumulates queued
// responses that flush in ONE send when the pipeline drains (the syscall-
// per-response pattern dominated apiserver CPU in live soaks).
struct ConnIO {
  int fd;
  std::string in;
  size_t off = 0;  // start of unconsumed bytes in `in`
  std::string out;

  bool flush() {
    if (out.empty()) return true;
    bool ok = send_all(fd, out.data(), out.size());
    out.clear();
    return ok;
  }
  // flush queued responses, then read more: only called when `in` lacks a
  // complete request, i.e. exactly when the pipeline has drained
  bool fill() {
    if (!flush()) return false;
    char tmp[65536];
    ssize_t n = recv(fd, tmp, sizeof tmp, 0);
    if (n <= 0) return false;
    in.append(tmp, n);
    return true;
  }
};

// Parses one request's head block (request line + headers) into req;
// shared by the blocking reader and the batch collector's buffered peek.
static bool parse_request_head(const std::string& head, Request& req) {
  size_t line_end = head.find("\r\n");
  std::string line = head.substr(0, line_end);
  size_t sp1 = line.find(' ');
  size_t sp2 = line.rfind(' ');
  if (sp1 == std::string::npos || sp2 <= sp1) return false;
  req.method = line.substr(0, sp1);
  std::string uri = line.substr(sp1 + 1, sp2 - sp1 - 1);
  size_t qm = uri.find('?');
  req.path = qm == std::string::npos ? uri : uri.substr(0, qm);
  req.query = qm == std::string::npos ? "" : uri.substr(qm + 1);

  size_t content_len = 0;
  req.close = false;
  req.auth.clear();
  req.lease_holder.clear();
  size_t pos = line_end == std::string::npos ? head.size() : line_end + 2;
  while (pos < head.size()) {
    size_t e = head.find("\r\n", pos);
    if (e == std::string::npos) e = head.size();
    std::string h = head.substr(pos, e - pos);
    pos = e + 2;
    size_t colon = h.find(':');
    if (colon == std::string::npos) continue;
    std::string k = h.substr(0, colon);
    std::transform(k.begin(), k.end(), k.begin(), ::tolower);
    std::string v = strip(h.substr(colon + 1));
    if (k == "content-length") content_len = (size_t)atoll(v.c_str());
    else if (k == "authorization") req.auth = v;
    else if (k == "x-kwok-lease-holder") req.lease_holder = v;
    else if (k == "connection") {
      std::transform(v.begin(), v.end(), v.begin(), ::tolower);
      if (v == "close") req.close = true;
    }
  }
  req.content_len = content_len;
  req.body.clear();
  req.body_read = false;
  return true;
}

// Reads one HTTP/1.1 request from the connection's pipelined buffer.
static bool read_request(ConnIO& io, Request& req) {
  // read_headers starts at the request's FIRST bytes (buffered for a
  // pipelined request, or the first fill otherwise) — keep-alive idle
  // time between requests is never attributed to the phase
  bool timed = timing_enabled();
  req.t_start = req.t_hdr = req.t_body = 0;
  if (timed && io.off < io.in.size()) req.t_start = now_ns();
  size_t hdr_end;
  while ((hdr_end = io.in.find("\r\n\r\n", io.off)) == std::string::npos) {
    if (io.off) {  // compact the consumed prefix before growing
      io.in.erase(0, io.off);
      io.off = 0;
    }
    if (io.in.size() > (32u << 20)) return false;
    if (!io.fill()) return false;
    if (timed && !req.t_start) req.t_start = now_ns();
  }
  std::string head = io.in.substr(io.off, hdr_end - io.off);
  if (!parse_request_head(head, req)) return false;
  io.off = hdr_end + 4;  // body bytes are consumed by read_body
  if (req.t_start) req.t_hdr = now_ns();
  return true;
}

// The batch collector's peek: parses the NEXT pipelined request ONLY
// when its head block AND body are already fully buffered — never a
// socket read, so collecting a batch can't stall behind a slow sender.
// Consumes the request from the buffer on success (headers + body).
static bool peek_buffered_request(ConnIO& io, Request& req) {
  size_t hdr_end = io.in.find("\r\n\r\n", io.off);
  if (hdr_end == std::string::npos) return false;
  Request tmp;
  tmp.t_start = tmp.t_hdr = tmp.t_body = 0;
  bool timed = timing_enabled();
  if (timed) tmp.t_start = now_ns();
  if (!parse_request_head(io.in.substr(io.off, hdr_end - io.off), tmp))
    return false;  // the blocking reader will hit the same bytes and close
  size_t total = hdr_end + 4 + tmp.content_len;
  if (io.in.size() < total) return false;
  tmp.body = io.in.substr(hdr_end + 4, tmp.content_len);
  tmp.body_read = true;
  if (tmp.t_start) tmp.t_hdr = tmp.t_body = now_ns();
  io.off = total;
  req = std::move(tmp);
  return true;
}


// Completes a request by reading its body off the pipeline (must be
// called exactly once per read_request before the next read_request, or
// the pipeline would parse body bytes as the next request's headers).
static bool read_body(ConnIO& io, Request& req) {
  if (req.body_read) return true;
  req.body_read = true;
  size_t total = io.off + req.content_len;
  while (io.in.size() < total) {
    if (!io.fill()) return false;
  }
  req.body = io.in.substr(io.off, req.content_len);
  io.off = total;
  if (io.off == io.in.size()) {
    io.in.clear();
    io.off = 0;
  } else if (io.off > (1u << 20)) {
    io.in.erase(0, io.off);
    io.off = 0;
  }
  if (req.t_start) req.t_body = now_ns();
  return true;
}

// Queues one response on the connection's out-buffer; flushed in one send
// when the request pipeline drains (ConnIO::fill) or past the size cap.
static bool queue_response(ConnIO& io, int code, const std::string& body,
                           const char* extra_headers = "",
                           const char* content_type = "application/json") {
  const char* reason = code == 200   ? "OK"
                       : code == 201 ? "Created"
                       : code == 401 ? "Unauthorized"
                       : code == 404 ? "Not Found"
                       : code == 429 ? "Too Many Requests"
                                     : "Error";
  char head[384];
  int hn = snprintf(head, sizeof head,
                    "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n%s"
                    "Content-Length: %zu\r\n\r\n",
                    code, reason, content_type, extra_headers, body.size());
  io.out.append(head, hn);
  io.out += body;
  // bound queued-response memory (large LIST pages): flush early
  if (io.out.size() > (4u << 20)) return io.flush();
  return true;
}

static std::string url_decode(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '%' && i + 2 < s.size()) {
      auto hexv = [](char c) -> int {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        return -1;
      };
      int hi = hexv(s[i + 1]), lo = hexv(s[i + 2]);
      if (hi >= 0 && lo >= 0) {
        out += (char)((hi << 4) | lo);
        i += 2;
        continue;
      }
    }
    out += s[i] == '+' ? ' ' : s[i];
  }
  return out;
}

static std::map<std::string, std::string> parse_query(const std::string& q) {
  std::map<std::string, std::string> out;
  size_t pos = 0;
  while (pos <= q.size()) {
    size_t amp = q.find('&', pos);
    std::string kv = q.substr(pos, amp == std::string::npos ? amp : amp - pos);
    if (!kv.empty()) {
      size_t e = kv.find('=');
      if (e == std::string::npos) out[url_decode(kv)] = "";
      else out[url_decode(kv.substr(0, e))] = url_decode(kv.substr(e + 1));
    }
    if (amp == std::string::npos) break;
    pos = amp + 1;
  }
  return out;
}

// path: /api/v1[/namespaces/NS]/(nodes|pods)[/NAME][/status]
struct PathMatch {
  bool ok = false;
  int kind = -1;
  std::string ns, name;
  bool status = false;
  bool binding = false;
  bool log = false;  // pods/NAME/log (GET-only; answers the kwok dialect)
};

static PathMatch match_path(const std::string& path) {
  PathMatch m;
  const std::string core = "/api/v1";
  const std::string rbac = "/apis/rbac.authorization.k8s.io/v1";
  // a real v1.19+ kube-scheduler records events via events.k8s.io/v1; both
  // groups route to the one events store (the real apiserver mirrors them)
  const std::string evg = "/apis/events.k8s.io/v1";
  std::string rest;
  bool is_rbac = false;
  bool is_events_group = false;
  if (path.rfind(rbac, 0) == 0) {
    rest = path.substr(rbac.size());
    is_rbac = true;
  } else if (path.rfind(evg, 0) == 0) {
    rest = path.substr(evg.size());
    is_events_group = true;
  } else if (path.rfind(core, 0) == 0) {
    rest = path.substr(core.size());
  } else {
    return m;
  }
  std::vector<std::string> parts;
  size_t pos = 0;
  while (pos < rest.size()) {
    if (rest[pos] == '/') {
      pos++;
      continue;
    }
    size_t slash = rest.find('/', pos);
    parts.push_back(
        rest.substr(pos, slash == std::string::npos ? slash : slash - pos));
    if (slash == std::string::npos) break;
    pos = slash + 1;
  }
  size_t i = 0;
  if (i + 1 < parts.size() && parts[i] == "namespaces") {
    m.ns = url_decode(parts[i + 1]);
    i += 2;
  }
  if (i >= parts.size()) return m;
  m.kind = kind_index(parts[i]);
  if (m.kind < 0) return m;
  // group membership: nodes/pods/events live under /api/v1, rbac kinds
  // under /apis/rbac.authorization.k8s.io/v1, events also under
  // /apis/events.k8s.io/v1 (mirrors mockserver.py)
  if (is_events_group) {
    if (m.kind != 6) return m;
  } else if (is_rbac != (m.kind >= 2 && m.kind <= 5)) {
    return m;
  }
  i++;
  if (i < parts.size()) {
    m.name = url_decode(parts[i]);
    i++;
  }
  if (i < parts.size()) {
    // subresources exist only where the real apiserver serves them:
    // status under nodes/pods, binding under pods (404 otherwise)
    if (parts[i] == "status" && m.kind <= 1) m.status = true;
    else if (parts[i] == "binding" && m.kind == 1) m.binding = true;
    else if (parts[i] == "log" && m.kind == 1) m.log = true;
    else return m;
    i++;
  }
  if (i != parts.size()) return m;
  m.ok = true;
  return m;
}

// A request the batched write transaction may absorb: a plain create /
// bind / patch / delete on a resource path. Fenced writes (the HA
// plane's X-Kwok-Lease-Holder) stay on the unary path, which holds
// lease_mu across its whole mutation; Connection: close and every
// read/stream/ops shape also stay unary.
static bool batchable_write(const Request& req) {
  if (req.close || !req.lease_holder.empty()) return false;
  PathMatch m = match_path(req.path);
  if (!m.ok || m.log) return false;
  if (req.method == "POST") return m.name.empty() ? !m.status : m.binding;
  if (req.method == "PATCH" || req.method == "DELETE")
    return !m.name.empty() && !m.binding;
  return false;
}

// Discovery documents served by GET on these exact paths; byte-content
// mirrors mockserver.py DISCOVERY (json.dumps compact) — parity-tested.
static const std::pair<const char*, const char*> DISCOVERY_DOCS[] = {
    {"/version",
     R"DISC({"major":"1","minor":"26","gitVersion":"v1.26.0-kwok-tpu","platform":"linux/amd64"})DISC"},
    {"/api",
     R"DISC({"kind":"APIVersions","versions":["v1"]})DISC"},
    {"/apis",
     R"DISC({"kind":"APIGroupList","apiVersion":"v1","groups":[{"name":"rbac.authorization.k8s.io","versions":[{"groupVersion":"rbac.authorization.k8s.io/v1","version":"v1"}],"preferredVersion":{"groupVersion":"rbac.authorization.k8s.io/v1","version":"v1"}},{"name":"events.k8s.io","versions":[{"groupVersion":"events.k8s.io/v1","version":"v1"}],"preferredVersion":{"groupVersion":"events.k8s.io/v1","version":"v1"}},{"name":"coordination.k8s.io","versions":[{"groupVersion":"coordination.k8s.io/v1","version":"v1"}],"preferredVersion":{"groupVersion":"coordination.k8s.io/v1","version":"v1"}}]})DISC"},
    {"/api/v1",
     R"DISC({"kind":"APIResourceList","groupVersion":"v1","resources":[{"name":"nodes","singularName":"","namespaced":false,"kind":"Node","verbs":["create","delete","get","list","patch","update","watch"]},{"name":"nodes/status","singularName":"","namespaced":false,"kind":"Node","verbs":["get","patch","update"]},{"name":"pods","singularName":"","namespaced":true,"kind":"Pod","verbs":["create","delete","get","list","patch","update","watch"]},{"name":"pods/status","singularName":"","namespaced":true,"kind":"Pod","verbs":["get","patch","update"]},{"name":"pods/binding","singularName":"","namespaced":true,"kind":"Pod","verbs":["create"]},{"name":"events","singularName":"","namespaced":true,"kind":"Event","verbs":["create","delete","get","list","patch","update","watch"]}]})DISC"},
    {"/apis/rbac.authorization.k8s.io/v1",
     R"DISC({"kind":"APIResourceList","groupVersion":"rbac.authorization.k8s.io/v1","resources":[{"name":"roles","singularName":"","namespaced":true,"kind":"Role","verbs":["create","delete","get","list","patch","update","watch"]},{"name":"rolebindings","singularName":"","namespaced":true,"kind":"RoleBinding","verbs":["create","delete","get","list","patch","update","watch"]},{"name":"clusterroles","singularName":"","namespaced":false,"kind":"ClusterRole","verbs":["create","delete","get","list","patch","update","watch"]},{"name":"clusterrolebindings","singularName":"","namespaced":false,"kind":"ClusterRoleBinding","verbs":["create","delete","get","list","patch","update","watch"]}]})DISC"},
    {"/apis/events.k8s.io/v1",
     R"DISC({"kind":"APIResourceList","groupVersion":"events.k8s.io/v1","resources":[{"name":"events","singularName":"","namespaced":true,"kind":"Event","verbs":["create","delete","get","list","patch","update","watch"]}]})DISC"},
    // the minimal Lease dialect: create / get / patch only (ISSUE 12)
    {"/apis/coordination.k8s.io/v1",
     R"DISC({"kind":"APIResourceList","groupVersion":"coordination.k8s.io/v1","resources":[{"name":"leases","singularName":"","namespaced":true,"kind":"Lease","verbs":["create","get","patch"]}]})DISC"},
};

// ------------------------------------------------------------------ app

// The 429 dialect, byte-identical to mockserver.py TOO_MANY_REQUESTS_BODY
// (parity-pinned): kube-apiserver's TooManyRequests Status plus a
// Retry-After hint the client's RetryPolicy must honor.
static const char* TOO_MANY_REQUESTS_BODY =
    "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":\"Failure\","
    "\"message\":\"Too many requests, please try again later.\","
    "\"reason\":\"TooManyRequests\",\"code\":429}";

struct App {
  Store store;
  std::mutex audit_mu;
  FILE* audit = nullptr;
  std::string data_file;
  // --token-auth-file bearer tokens, one per CSV row (empty = authn off);
  // kube-apiserver accepts every row of the file, not just the first
  std::set<std::string> auth_tokens;
  int listen_fd = -1;
  std::atomic<bool> stopping{false};
  // two-band max-inflight admission (kube-apiserver
  // --max-requests-inflight / --max-mutating-requests-inflight; KEP-1040
  // reject-don't-queue shape). 0 = band off (the default: the admission
  // branch is never entered, zero per-request cost). Index 0 = readonly
  // (LIST/GET), 1 = mutating (POST/PATCH/DELETE); watches are
  // long-running and exempt, bounded by watch_backlog() instead.
  long max_inflight_band[2] = {0, 0};
  std::atomic<long> inflight[2] = {{0}, {0}};
  std::atomic<long> rejected[2] = {{0}, {0}};

  void audit_line(const std::string& method, const std::string& uri, int code);
  void handle_conn(int fd);
  bool handle_request(ConnIO& io, Request& req);
  size_t exec_write_batch(ConnIO& io, std::vector<Request>& batch);
  void evict_events(double* fanout_us);
  std::string metrics_text();
  std::string watchers_dump_json();
  std::string snapshot_dump();
  void restore_load(const JVal& data);
  void seed_rbac();
  void persist();
};

static App* g_app = nullptr;

void App::audit_line(const std::string& method, const std::string& uri,
                     int code) {
  if (!audit) return;
  // HTTP method + URI -> k8s audit verb (matches the Python mock)
  std::string verb;
  if (method == "GET") {
    verb = "get";
    size_t qm = uri.find('?');
    std::string path = qm == std::string::npos ? uri : uri.substr(0, qm);
    std::string query = qm == std::string::npos ? "" : uri.substr(qm + 1);
    auto q = parse_query(query);
    auto w = q.find("watch");
    if (w != q.end() && (w->second == "true" || w->second == "1")) {
      verb = "watch";
    } else {
      PathMatch m = match_path(path);
      if (m.ok && m.name.empty()) verb = "list";
    }
  } else if (method == "POST") verb = "create";
  else if (method == "PUT") verb = "update";
  else if (method == "PATCH") verb = "patch";
  else if (method == "DELETE") verb = "delete";
  else {
    verb = method;
    std::transform(verb.begin(), verb.end(), verb.begin(), ::tolower);
  }
  std::string line =
      "{\"kind\": \"Event\", \"apiVersion\": \"audit.k8s.io/v1\", "
      "\"level\": \"Metadata\", \"stage\": \"ResponseComplete\", \"verb\": \"";
  line += verb;
  line += "\", \"requestURI\": \"";
  json_escape(line, uri);
  line += "\", \"responseStatus\": {\"code\": ";
  line += std::to_string(code);
  line += "}, \"stageTimestamp\": \"";
  line += now_rfc3339();
  line += "\"}\n";
  std::lock_guard<std::mutex> lk(audit_mu);
  fwrite(line.data(), 1, line.size(), audit);
  fflush(audit);
}

std::string App::metrics_text() {
  // overload-protection surface, HELP text byte-identical to
  // kwok_tpu/telemetry/apiserver_metrics.py (both servers scrape alike)
  static const char* BANDS[2] = {"readonly", "mutating"};
  std::string out;
  out +=
      "# HELP kwok_apiserver_inflight Requests currently admitted per "
      "max-inflight band (readonly=LIST/GET, mutating=POST/PATCH/DELETE; "
      "watches exempt)\n# TYPE kwok_apiserver_inflight gauge\n";
  for (int b = 0; b < 2; b++)
    out += "kwok_apiserver_inflight{band=\"" + std::string(BANDS[b]) +
           "\"} " + std::to_string(inflight[b].load()) + "\n";
  out +=
      "# HELP kwok_apiserver_rejected_total Requests rejected with 429 + "
      "Retry-After because the band's max-inflight limit was saturated\n"
      "# TYPE kwok_apiserver_rejected_total counter\n";
  for (int b = 0; b < 2; b++)
    out += "kwok_apiserver_rejected_total{band=\"" + std::string(BANDS[b]) +
           "\"} " + std::to_string(rejected[b].load()) + "\n";
  out +=
      "# HELP kwok_watch_terminations_total Watch streams closed by the "
      "server (slow=send-buffer overflow from a consumer that stopped "
      "reading, deadline=timeoutSeconds expiry)\n"
      "# TYPE kwok_watch_terminations_total counter\n";
  out += "kwok_watch_terminations_total{reason=\"slow\"} " +
         std::to_string(g_watch_term_slow.load()) + "\n";
  out += "kwok_watch_terminations_total{reason=\"deadline\"} " +
         std::to_string(g_watch_term_deadline.load()) + "\n";

  // ---- phase-timing families (ISSUE 11): HELP text, bucket labels and
  // the full phase/verb sample matrix are byte-identical to
  // telemetry/apiserver_metrics.render_timing_metrics — only the sample
  // values differ (the parity twin masks them)
  char fbuf[64];
  auto hist_lines = [&out, &fbuf](const char* name, const char* label,
                                  const char* value, const PhaseHist& h) {
    uint64_t acc = 0;
    for (int i = 0; i < N_TBUCKETS; i++) {
      acc += h.buckets[i].load(std::memory_order_relaxed);
      out += std::string(name) + "_bucket{" + label + "=\"" + value +
             "\",le=\"" + TBUCKET_LE[i] + "\"} " + std::to_string(acc) +
             "\n";
    }
    // count is read LAST; clamp so a mid-scrape observe can never leave
    // the +Inf bucket (rendered from count) below a finite bucket
    uint64_t c = h.count.load(std::memory_order_relaxed);
    acc += h.buckets[N_TBUCKETS].load(std::memory_order_relaxed);
    if (c < acc) c = acc;
    out += std::string(name) + "_bucket{" + label + "=\"" + value +
           "\",le=\"+Inf\"} " + std::to_string(c) + "\n";
    snprintf(fbuf, sizeof fbuf, "%.9f",
             (double)h.sum_ns.load(std::memory_order_relaxed) / 1e9);
    out += std::string(name) + "_sum{" + label + "=\"" + value + "\"} " +
           fbuf + "\n";
    out += std::string(name) + "_count{" + label + "=\"" + value + "\"} " +
           std::to_string(c) + "\n";
  };
  out +=
      "# HELP kwok_apiserver_request_phase_seconds Per-request phase "
      "seconds inside the mock apiserver (read_headers+read_body+parse+"
      "commit+encode reconcile to the request total; fanout is the "
      "serialize-once ring encode+append subset of commit and is excluded "
      "from the sum)\n# TYPE kwok_apiserver_request_phase_seconds histogram\n";
  for (int p = 0; p < N_PHASES; p++)
    hist_lines("kwok_apiserver_request_phase_seconds", "phase",
               PHASE_NAMES[p], g_phase_hist[p]);
  out +=
      "# HELP kwok_apiserver_request_seconds End-to-end seconds per "
      "unary request by audit verb (first request bytes to response "
      "queued; watch streams are long-running and excluded)\n"
      "# TYPE kwok_apiserver_request_seconds histogram\n";
  for (int v = 0; v < N_VERBS; v++)
    hist_lines("kwok_apiserver_request_seconds", "verb", VERB_NAMES[v],
               g_verb_hist[v]);
  out +=
      "# HELP kwok_watch_fanout_total Watch events delivered to "
      "individual watchers via the broadcast ring (events x live "
      "watchers of the kind at emit; fanout_sum over this count is the "
      "AMORTIZED per-watcher encode cost \xe2\x80\x94 the ring encodes once and "
      "shares the bytes)\n"
      "# TYPE kwok_watch_fanout_total counter\n";
  out += "kwok_watch_fanout_total " +
         std::to_string(g_fanout_pushes.load()) + "\n";
  long n_watch = 0, bmax = 0, btotal = 0, encodes = 0;
  {
    std::lock_guard<std::mutex> lk(store.ring_mu);
    for (const auto& w : store.watches) {
      if (w->closed) continue;
      long d = (long)(store.ring_next - w->cursor);
      n_watch++;
      btotal += d;
      if (d > bmax) bmax = d;
    }
    encodes = store.encode_total;
  }
  out +=
      "# HELP kwok_apiserver_watchers Live watch streams currently "
      "registered\n# TYPE kwok_apiserver_watchers gauge\n";
  out += "kwok_apiserver_watchers " + std::to_string(n_watch) + "\n";
  out +=
      "# HELP kwok_watch_backlog_events Per-watcher ring-cursor lag "
      "across live watches (agg=max/total) and the high-watermark of "
      "retained lag (agg=peak; never exceeds KWOK_TPU_WATCH_BACKLOG "
      "while the slow-consumer cap enforces \xe2\x80\x94 the bounded-buffer "
      "proof, now measured as ring lag)\n"
      "# TYPE kwok_watch_backlog_events gauge\n";
  out += "kwok_watch_backlog_events{agg=\"max\"} " +
         std::to_string(bmax) + "\n";
  out += "kwok_watch_backlog_events{agg=\"total\"} " +
         std::to_string(btotal) + "\n";
  out += "kwok_watch_backlog_events{agg=\"peak\"} " +
         std::to_string(g_backlog_peak.load()) + "\n";
  out +=
      "# HELP kwok_watch_ring_lag Ring-cursor lag behind the "
      "serialize-once broadcast ring head per live watch stream "
      "(agg=max/total) and its all-time high-watermark (agg=peak, "
      "clamped to the backlog cap on a slow-close; identical to "
      "kwok_watch_backlog_events by construction \xe2\x80\x94 the explicit "
      "ring-surface name)\n"
      "# TYPE kwok_watch_ring_lag gauge\n";
  out += "kwok_watch_ring_lag{agg=\"max\"} " + std::to_string(bmax) + "\n";
  out += "kwok_watch_ring_lag{agg=\"total\"} " +
         std::to_string(btotal) + "\n";
  out += "kwok_watch_ring_lag{agg=\"peak\"} " +
         std::to_string(g_backlog_peak.load()) + "\n";
  out +=
      "# HELP kwok_watch_encode_total Watch events encoded into the "
      "broadcast ring \xe2\x80\x94 exactly ONE encode per event no matter the "
      "watcher count (the serialize-once proof; "
      "kwok_watch_fanout_total counts the deliveries the shared bytes "
      "fan out to)\n"
      "# TYPE kwok_watch_encode_total counter\n";
  out += "kwok_watch_encode_total " + std::to_string(encodes) + "\n";
  out +=
      "# HELP kwok_watch_cursor_lag_events Final ring-cursor lag (events "
      "behind the broadcast ring head) observed once per watch close: "
      "slow terminations record the overflow that killed the stream, "
      "graceful closes the drained tail; per-watcher live lag is GET "
      "/debug/watchers\n"
      "# TYPE kwok_watch_cursor_lag_events histogram\n";
  {
    uint64_t acc = 0;
    for (int i = 0; i < N_LBUCKETS; i++) {
      acc += g_lag_buckets[i].load(std::memory_order_relaxed);
      out += "kwok_watch_cursor_lag_events_bucket{le=\"" +
             std::string(LBUCKET_LE[i]) + "\"} " + std::to_string(acc) +
             "\n";
    }
    uint64_t c = g_lag_count.load(std::memory_order_relaxed);
    acc += g_lag_buckets[N_LBUCKETS].load(std::memory_order_relaxed);
    if (c < acc) c = acc;  // +Inf can never render below a finite bucket
    out += "kwok_watch_cursor_lag_events_bucket{le=\"+Inf\"} " +
           std::to_string(c) + "\n";
    out += "kwok_watch_cursor_lag_events_sum " +
           std::to_string(g_lag_sum.load(std::memory_order_relaxed)) + "\n";
    out += "kwok_watch_cursor_lag_events_count " + std::to_string(c) + "\n";
  }
  return out;
}

std::string App::watchers_dump_json() {
  // GET /debug/watchers (ISSUE 16): the watch-plane census — one
  // consistent ring-lock read of every live watch. Key order and value
  // vocabulary mirror mockserver.py watchers_doc (schema parity-pinned
  // by kwok_tpu.telemetry.timeline.check_watchers).
  long cap = watch_backlog();
  double now = wall_unix_s();
  char num[64];
  std::string ws;
  long count = 0, parked = 0;
  {
    std::lock_guard<std::mutex> lk(store.ring_mu);
    for (const auto& w : store.watches) {
      if (w->closed) continue;
      long lag = (long)(store.ring_next - w->cursor);
      if (lag < 0) lag = 0;
      long replay = w->replay_pending.load(std::memory_order_relaxed);
      // fully drained: its delivery thread is parked in the ring cv
      // wait — the per-watcher thread cost the reactor rewrite erases
      if (lag == 0 && replay == 0) parked++;
      const char* risk =
          lag == 0 ? "none" : (lag <= cap / 2 ? "lagging" : "at_risk");
      if (count) ws += ',';
      count++;
      ws += "{\"kind\":\"";
      ws += KIND_NAMES[w->kind];
      ws += "\",\"lag_events\":" + std::to_string(lag);
      ws += ",\"replay_pending\":" + std::to_string(replay);
      double age = now - w->created_unix;
      if (age < 0) age = 0;
      snprintf(num, sizeof num, ",\"age_s\":%.3f", age);
      ws += num;
      ws += ",\"band\":\"none\",\"risk\":\"";  // watches are band-exempt
      ws += risk;
      ws += "\"}";
    }
  }
  std::string out =
      "{\"server\":\"native\",\"backlog_cap\":" + std::to_string(cap);
  out += ",\"thread_per_watcher\":true,\"count\":" + std::to_string(count);
  out += ",\"parked_threads\":" + std::to_string(parked);
  out += ",\"watchers\":[" + ws + "]}";
  return out;
}

std::string App::snapshot_dump() {
  // Sharded walk, rolled back through the undo log to ONE revision
  // across every kind (the mock's consistent etcd snapshot); objects are
  // ordered by (namespace, name) — the maps' natural order, pinned by
  // the snapshot-ordering parity twin.
  std::map<Key, EntryPtr> snap[NKINDS];
  int64_t rv_start = 0;
  for (int attempt = 0; attempt < 4; attempt++) {
    {
      std::lock_guard<std::mutex> lk(store.mu);
      rv_start = store.rv;
    }
    for (int k = 0; k < NKINDS; k++) {
      snap[k].clear();
      for (auto& ns_sh : store.kind_shards(k)) {
        std::lock_guard<std::mutex> sl(ns_sh.second->smu);
        for (auto& kv : ns_sh.second->objs)
          snap[k][Key{ns_sh.first, kv.first}] = kv.second;
      }
    }
    std::lock_guard<std::mutex> lk(store.mu);
    if (rv_window() > 0 && rv_start < store.compacted_rv && attempt < 3)
      continue;  // compaction raced the walk: retry
    for (auto u = store.undo.rbegin(); u != store.undo.rend(); ++u) {
      if (u->rv <= rv_start) break;
      if (u->prev)
        snap[u->kind][u->key] = u->prev;
      else
        snap[u->kind].erase(u->key);
    }
    break;
  }
  std::string out = "{\"resourceVersion\":";
  out += std::to_string(rv_start);
  out += ",\"objects\":{";
  for (int k = 0; k < NKINDS; k++) {
    if (k) out += ',';
    out += '"';
    out += KIND_NAMES[k];
    out += "\":[";
    bool first = true;
    for (auto& kv : snap[k]) {
      if (!first) out += ',';
      first = false;
      out += kv.second->bytes;
    }
    out += ']';
  }
  out += "}}";
  return out;
}

void App::restore_load(const JVal& data) {
  // Build the fresh shard registry OFF-lock, swap it in, then compact
  // and close watches: a reader holding an old shard sees the
  // pre-restore world, never a torn one.
  std::map<std::string, ShardPtr> fresh[NKINDS];
  long counts[NKINDS] = {};
  std::map<std::string, long> phases[NKINDS];
  const JVal* objects = data.find("objects");
  if (objects && objects->type == JVal::OBJ) {
    for (int k = 0; k < NKINDS; k++) {
      const JVal* list = objects->find(KIND_NAMES[k]);
      if (!list || list->type != JVal::ARR) continue;
      for (const JVal& obj : list->arr) {
        Key key = Store::obj_key(obj);
        if (key.second.empty()) continue;
        auto& sh = fresh[k][key.first];
        if (!sh) sh = std::make_shared<Shard>();
        EntryPtr e = publish(obj);
        if (!sh->objs.count(key.second)) counts[k]++;
        phases[k][field_str(e->obj, "status.phase")]++;
        sh->objs[key.second] = e;
      }
    }
  }
  {
    std::lock_guard<std::mutex> sl(store.shards_mu);
    for (int k = 0; k < NKINDS; k++) store.shards[k].swap(fresh[k]);
  }
  {
    std::lock_guard<std::mutex> lk(store.mu);
    for (int k = 0; k < NKINDS; k++) {
      store.phase_idx[k] = std::move(phases[k]);
      store.obj_count[k] = counts[k];
    }
    int64_t rv = 0;
    const JVal* rvv = data.find("resourceVersion");
    if (rvv && rvv->type == JVal::NUM) rv = atoll(rvv->s.c_str());
    store.rv = std::max(store.rv, rv) + 1;
    // history predates the restore: compact so resumed watches and
    // continue tokens from the old world get 410 and re-list
    store.history.clear();
    store.undo.clear();
    store.compacted_rv = store.rv;
    std::lock_guard<std::mutex> rl(store.ring_mu);
    for (auto& w : store.watches) store.close_watch_locked(w, false);
    store.watches.clear();
    store.ring.clear();
    store.ring_min = store.ring_next;
  }
  store.ring_cv.notify_all();
}

// Bootstrap RBAC policy for --authorization: a representative subset of
// what the real apiserver's bootstrap controller creates, byte-identical in
// content to mockserver.py BOOTSTRAP_RBAC (the authorization e2e + parity
// tests assert the two servers seed the same objects).
static const char* BOOTSTRAP_RBAC_JSON = R"JSON({
"clusterroles": [
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"ClusterRole",
  "metadata":{"name":"cluster-admin","labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "rules":[{"apiGroups":["*"],"resources":["*"],"verbs":["*"]},
           {"nonResourceURLs":["*"],"verbs":["*"]}]},
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"ClusterRole",
  "metadata":{"name":"system:discovery","labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "rules":[{"nonResourceURLs":["/api","/api/*","/apis","/apis/*","/healthz","/version"],"verbs":["get"]}]},
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"ClusterRole",
  "metadata":{"name":"system:kwok-controller","labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "rules":[{"apiGroups":[""],"resources":["nodes","pods"],"verbs":["get","watch","list"]},
           {"apiGroups":[""],"resources":["nodes/status","pods/status"],"verbs":["update","patch"]}]}
],
"clusterrolebindings": [
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"ClusterRoleBinding",
  "metadata":{"name":"cluster-admin","labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "roleRef":{"apiGroup":"rbac.authorization.k8s.io","kind":"ClusterRole","name":"cluster-admin"},
  "subjects":[{"apiGroup":"rbac.authorization.k8s.io","kind":"Group","name":"system:masters"}]},
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"ClusterRoleBinding",
  "metadata":{"name":"system:kwok-controller","labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "roleRef":{"apiGroup":"rbac.authorization.k8s.io","kind":"ClusterRole","name":"system:kwok-controller"},
  "subjects":[{"kind":"ServiceAccount","name":"kwok-controller","namespace":"kube-system"}]}
],
"roles": [
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"Role",
  "metadata":{"name":"extension-apiserver-authentication-reader","namespace":"kube-system",
              "labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "rules":[{"apiGroups":[""],"resources":["configmaps"],
            "resourceNames":["extension-apiserver-authentication"],
            "verbs":["get","list","watch"]}]}
],
"rolebindings": [
 {"apiVersion":"rbac.authorization.k8s.io/v1","kind":"RoleBinding",
  "metadata":{"name":"system::extension-apiserver-authentication-reader","namespace":"kube-system",
              "labels":{"kubernetes.io/bootstrapping":"rbac-defaults"}},
  "roleRef":{"apiGroup":"rbac.authorization.k8s.io","kind":"Role",
             "name":"extension-apiserver-authentication-reader"},
  "subjects":[{"apiGroup":"rbac.authorization.k8s.io","kind":"User",
               "name":"system:kube-controller-manager"}]}
]
})JSON";

void App::seed_rbac() {
  // materialize the literal: JParser keeps pointers into the string
  const std::string text = BOOTSTRAP_RBAC_JSON;
  JParser p(text);
  JVal data = p.parse();
  if (!p.ok) return;
  for (const auto& kv : data.obj) {
    int k = kind_index(kv.first);
    if (k < 0 || kv.second.type != JVal::ARR) continue;
    for (const JVal& tmpl : kv.second.arr) {
      Key key = Store::obj_key(tmpl);
      if (key.second.empty()) continue;
      ShardPtr sh = store.shard_of(k, key.first);
      std::lock_guard<std::mutex> sl(sh->smu);
      if (sh->objs.count(key.second)) continue;
      JVal obj = tmpl;  // idempotent create-if-absent (data-file restarts)
      JVal& meta = obj.get_or_insert_obj("metadata");
      meta.set("creationTimestamp", JVal::str(now_rfc3339()));
      // seeding happens before the listener accepts watchers, so the
      // ring append inside commit is vacuous (no watchers registered)
      std::lock_guard<std::mutex> lk(store.mu);
      EntryPtr e = store.commit_locked(k, "ADDED", std::move(obj), key,
                                       nullptr, nullptr, sh.get(),
                                       /*stamp_uid=*/true);
      sh->objs[key.second] = e;
    }
  }
}

// The real apiserver expires events on a ~1h etcd lease (re-leased on
// every write); the mock bounds the store by count — the least-recently-
// written event (smallest resourceVersion) is evicted after an insert
// pushes past the cap. Runs OUTSIDE the creating shard's critical
// section: the victim may live in another namespace shard, and shard
// locks never nest (mirrors mockserver._evict_events_overflow).
void App::evict_events(double* fanout_us) {
  int ek = kind_index("events");
  long cap = events_cap();
  if (cap <= 0) return;
  while (true) {
    {
      std::lock_guard<std::mutex> lk(store.mu);
      if (store.obj_count[ek] <= cap) return;
    }
    // find the min-rv victim across the kind's shards (O(cap) scan,
    // paid only past the cap; never the just-created entry — its rv is
    // the newest)
    std::string v_ns, v_name;
    long long best = 0;
    bool have = false;
    for (auto& ns_sh : store.kind_shards(ek)) {
      std::lock_guard<std::mutex> sl(ns_sh.second->smu);
      for (auto& kv : ns_sh.second->objs) {
        const JVal* mv = kv.second->obj.find("metadata");
        const JVal* rv = mv ? mv->find("resourceVersion") : nullptr;
        long long n = rv ? atoll(rv->s.c_str()) : 0;
        if (!have || n < best) {
          have = true;
          best = n;
          v_ns = ns_sh.first;
          v_name = kv.first;
        }
      }
    }
    if (!have) return;
    ShardPtr sh = store.shard_of(ek, v_ns, /*create=*/false);
    if (!sh) return;
    bool erased = false;
    {
      std::lock_guard<std::mutex> sl(sh->smu);
      auto it = sh->objs.find(v_name);
      if (it != sh->objs.end()) {
        // deletion is a write: bump like the explicit DELETE path, so
        // the DELETED event gets its own revision (rv-resuming watchers
        // would otherwise never see the eviction)
        JVal vobj = it->second->obj;  // copy-on-write
        EntryPtr vprev = it->second;
        sh->objs.erase(it);
        std::lock_guard<std::mutex> lk(store.mu);
        store.commit_locked(ek, "DELETED", std::move(vobj),
                            Key{v_ns, v_name}, std::move(vprev),
                            fanout_us, sh.get());
        erased = true;
      }
    }
    if (erased) store.ring_cv.notify_all();
    // raced evictions still make progress (the other thread erased);
    // loop re-checks the population either way
  }
}

void App::persist() {
  if (data_file.empty()) return;
  std::string tmp = data_file + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) return;
  std::string dump = snapshot_dump();
  fwrite(dump.data(), 1, dump.size(), f);
  fclose(f);
  rename(tmp.c_str(), data_file.c_str());
}

// returns false when the connection must close
bool App::handle_request(ConnIO& io, Request& req) {
  int fd = io.fd;  // streaming paths (watch) write directly
  auto q = parse_query(req.query);
  std::string uri = req.path;
  if (!req.query.empty()) uri += "?" + req.query;

  // phase timing (ISSUE 11): boundary marks accumulate into pt; the
  // respond chokepoint closes the request and observes/records it.
  // band is declared up here so the finisher can label flight records.
  PhaseTimer pt;
  int band = -1;
  auto finish_timing = [&](int code) {
    if (!req.t_start) return;
    pt.mark(PH_ENCODE);  // response build + audit + queueing since the
                         // last mark (commit end, or body read)
    uint64_t t_end = pt.on ? pt.last : now_ns();
    uint64_t t0 = req.t_start;
    req.t_start = 0;  // one observation per request
    PathMatch fm = match_path(req.path);
    if (!fm.ok) return;  // ops/debug paths stay untimed (Python parity)
    bool is_watch = false;
    if (req.method == "GET") {
      auto wq = q.find("watch");
      is_watch =
          wq != q.end() && (wq->second == "true" || wq->second == "1");
    }
    double total_us = (double)(t_end - t0) / 1000.0;
    uint64_t t_hdr = req.t_hdr ? req.t_hdr : t0;
    uint64_t t_body = req.t_body ? req.t_body : t_hdr;
    pt.us[PH_READ_HEADERS] = (double)(t_hdr - t0) / 1000.0;
    pt.us[PH_READ_BODY] = (double)(t_body - t_hdr) / 1000.0;
    g_phase_hist[PH_READ_HEADERS].observe_ns(t_hdr - t0);
    g_phase_hist[PH_READ_BODY].observe_ns(t_body - t_hdr);
    g_phase_hist[PH_COMMIT].observe_ns(
        (uint64_t)(pt.us[PH_COMMIT] * 1000.0));
    g_phase_hist[PH_ENCODE].observe_ns(
        (uint64_t)(pt.us[PH_ENCODE] * 1000.0));
    if (pt.parsed)
      g_phase_hist[PH_PARSE].observe_ns(
          (uint64_t)(pt.us[PH_PARSE] * 1000.0));
    if (pt.us[PH_FANOUT] > 0)
      g_phase_hist[PH_FANOUT].observe_ns(
          (uint64_t)(pt.us[PH_FANOUT] * 1000.0));
    int vi = 5;  // other (includes watch-handshake errors, Python parity)
    if (req.method == "GET" && !is_watch) vi = fm.name.empty() ? 1 : 0;
    else if (req.method == "POST") vi = 2;
    else if (req.method == "PATCH") vi = 3;
    else if (req.method == "DELETE") vi = 4;
    g_verb_hist[vi].observe_ns(t_end - t0);
    FlightRec rec;
    rec.method = req.method;
    rec.path = uri;
    rec.status = code;
    // band by REQUEST SHAPE (Python _admission_band parity): labeled
    // even when no max-inflight limit is configured
    if (band == 0 || (band < 0 && req.method == "GET" && !is_watch))
      rec.band = "readonly";
    else if (band == 1 ||
             (band < 0 && (req.method == "POST" || req.method == "PATCH" ||
                           req.method == "DELETE")))
      rec.band = "mutating";
    else
      rec.band = "none";
    rec.ts_unix = wall_unix_s() - total_us / 1e6;
    rec.total_us = total_us;
    for (int p = 0; p < N_PHASES; p++) rec.phases_us[p] = pt.us[p];
    flight_record(std::move(rec));
  };

  // Ring wake-ups leave AFTER the response is queued (ISSUE 13):
  // waking a watcher cohort inside the commit window put the whole
  // thundering herd on the requester's critical path — the store is
  // consistent the moment the clock lock dropped, so the fanout wake
  // rides behind the answer instead of in front of it.
  bool wake_ring = false;
  auto respond = [&](int code, const std::string& body,
                     const char* extra = "",
                     const char* ctype = "application/json") {
    audit_line(req.method, uri, code);
    bool ok = queue_response(io, code, body, extra, ctype);
    finish_timing(code);
    if (wake_ring) {
      // deferred fanout wake (see above): the answer goes ON THE WIRE
      // first — on an oversubscribed host a thousand woken watcher
      // threads would otherwise run before the requester's flush
      wake_ring = false;
      if (!io.flush()) ok = false;
      store.ring_cv.notify_all();
    }
    if (req.close) {
      io.flush();
      return false;
    }
    return ok;
  };
  // arm the phase accumulator once the body is consumed (read_body
  // stamped t_body); every later mark() is one clock read
  auto arm_timer = [&] {
    if (req.t_start) {
      pt.on = true;
      pt.last = req.t_body ? req.t_body : now_ns();
    }
  };

  // ---- max-inflight admission (two bands; watches + non-resource paths
  // exempt). The band slot spans the request's whole lifetime — body read
  // included — so saturation is observable; a rejected request answers
  // 429 + Retry-After NOW instead of queueing, after draining its body so
  // the keep-alive pipeline stays parseable.
  if (max_inflight_band[0] > 0 || max_inflight_band[1] > 0) {
    PathMatch am = match_path(req.path);
    if (am.ok) {
      if (req.method == "GET") {
        auto wq = q.find("watch");
        bool is_watch =
            wq != q.end() && (wq->second == "true" || wq->second == "1");
        if (!is_watch) band = 0;
      } else if (req.method == "POST" || req.method == "PATCH" ||
                 req.method == "DELETE") {
        band = 1;
      }
    }
  }
  struct SlotRelease {
    std::atomic<long>* c = nullptr;
    ~SlotRelease() {
      if (c) c->fetch_sub(1);
    }
  } slot;
  if (band >= 0 && max_inflight_band[band] > 0) {
    if (inflight[band].fetch_add(1) + 1 > max_inflight_band[band]) {
      inflight[band].fetch_sub(1);
      rejected[band].fetch_add(1);
      if (!read_body(io, req)) return false;  // drain for keep-alive
      arm_timer();
      return respond(429, TOO_MANY_REQUESTS_BODY, "Retry-After: 1\r\n");
    }
    slot.c = &inflight[band];
  }
  if (!read_body(io, req)) return false;
  arm_timer();

  if (req.method == "GET" && req.path == "/healthz")
    return respond(200, "ok");
  if (req.method == "GET" && req.path == "/metrics")
    return respond(200, metrics_text(), "", "text/plain; version=0.0.4");
  if (req.method == "GET" && req.path == "/debug/flight")
    // flight recorder dump (anonymous, like /metrics): the bounded ring
    // of recent request records — the engine auto-grabs it on a /readyz
    // degradation edge
    return respond(200, flight_dump_json());
  if (req.method == "GET" && req.path == "/debug/watchers")
    // watch-plane census (anonymous, like /debug/flight): per-watcher
    // ring-cursor lag, replay backlog, age, termination risk
    return respond(200, watchers_dump_json());
  // bearer-token authn (--token-auth-file): /healthz stays anonymous (the
  // components' --authorization-always-allow-paths contract)
  if (!auth_tokens.empty() &&
      (req.auth.rfind("Bearer ", 0) != 0 ||
       !auth_tokens.count(req.auth.substr(7))))
    return respond(401,
                   "{\"kind\":\"Status\",\"apiVersion\":\"v1\","
                   "\"status\":\"Failure\",\"reason\":\"Unauthorized\","
                   "\"message\":\"Unauthorized\",\"code\":401}");
  if (req.method == "GET") {
    for (const auto& d : DISCOVERY_DOCS)
      if (req.path == d.first) return respond(200, d.second);
  }
  if (req.method == "GET" && req.path == "/snapshot")
    return respond(200, snapshot_dump());
  if (req.method == "POST" && req.path == "/restore") {
    JParser p(req.body);
    JVal data = p.parse();
    restore_load(data);
    return respond(200, "{\"kind\":\"Status\",\"status\":\"Success\"}");
  }
  if (req.method == "POST" && req.path == "/compact") {
    // the mock's `etcdctl compact`: expire the watch cache and in-flight
    // continue tokens NOW (test/ops hook; the real apiserver compacts
    // every 5 minutes)
    int64_t crv;
    {
      std::lock_guard<std::mutex> lk(store.mu);
      store.history.clear();
      store.undo.clear();
      store.compacted_rv = store.rv;
      crv = store.compacted_rv;
    }
    return respond(200,
                   "{\"compactedRevision\":" + std::to_string(crv) + "}");
  }

  // ---- coordination.k8s.io/v1 leases (ISSUE 12): the leadership plane's
  // minimal dialect — create / GET / PATCH-renew, arbitrated under the
  // store lock by the SERVER's clock. Deliberately outside match_path:
  // exempt from admission/timing like every non-resource path, mirrored
  // byte-for-byte with mockserver.py (parity twins pin it).
  {
    static const std::string lease_prefix =
        "/apis/coordination.k8s.io/v1/namespaces/";
    if (req.path.rfind(lease_prefix, 0) == 0) {
      std::string rest = req.path.substr(lease_prefix.size());
      size_t s1 = rest.find('/');
      std::string lns =
          s1 == std::string::npos ? "" : url_decode(rest.substr(0, s1));
      std::string tail = s1 == std::string::npos ? "" : rest.substr(s1 + 1);
      std::string lname;
      bool routed = false;
      if (tail == "leases") routed = true;
      else if (tail.rfind("leases/", 0) == 0) {
        lname = url_decode(tail.substr(7));
        routed = !lname.empty() && lname.find('/') == std::string::npos;
      }
      if (!lns.empty() && routed) {
        Key lkey{lns, lname};
        if (req.method == "GET" && !lname.empty()) {
          int code = 404;
          std::string body = "{\"kind\":\"Status\",\"code\":404}";
          {
            std::lock_guard<std::mutex> lk(store.lease_mu);
            auto it = store.leases.find(lkey);
            if (it != store.leases.end()) {
              code = 200;
              body = lease_render(lns, lname, it->second);
            }
          }
          return respond(code, body);
        }
        if (req.method == "POST" && lname.empty()) {
          JParser p(req.body);
          JVal obj = p.parse();
          if (!p.ok || obj.type != JVal::OBJ)
            return respond(400, "{\"kind\":\"Status\",\"code\":400}");
          const JVal* meta = obj.find("metadata");
          const JVal* nm = meta && meta->is_obj() ? meta->find("name")
                                                  : nullptr;
          std::string name =
              nm && nm->type == JVal::STR ? nm->s : std::string();
          if (name.empty())
            return respond(400, "{\"kind\":\"Status\",\"code\":400}");
          std::string holder;
          long duration = 0;
          lease_spec_fields(obj, holder, duration);
          int code;
          std::string body;
          {
            std::lock_guard<std::mutex> lk(store.lease_mu);
            if (store.leases.count(Key{lns, name})) {
              code = 409;
              body =
                  "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
                  "\"Failure\",\"message\":\"leases \\\"";
              json_escape(body, name);
              body +=
                  "\\\" already exists\",\"reason\":\"AlreadyExists\","
                  "\"code\":409}";
            } else {
              double now = wall_unix_s();
              std::string stamp = now_rfc3339();
              int64_t lrv;
              {
                // lease writes share the store clock (lease 86 -> ring
                // 88 in the declared order; shards never involved)
                std::lock_guard<std::mutex> rk(store.mu);
                lrv = ++store.rv;
              }
              LeaseRec L;
              L.holder = holder;
              L.duration = duration;
              L.acquire = L.renew = now;
              L.transitions = 0;
              L.created = L.acquire_str = L.renew_str = stamp;
              L.uid = "uid-" + std::to_string(lrv);
              L.rv = lrv;
              store.leases[Key{lns, name}] = L;
              code = 201;
              body = lease_render(lns, name, L);
            }
          }
          return respond(code, body);
        }
        if (req.method == "PATCH" && !lname.empty()) {
          JParser p(req.body);
          JVal patch = p.parse();
          if (!p.ok)
            return respond(400, "{\"kind\":\"Status\",\"code\":400}");
          std::string holder;
          long duration = 0;
          lease_spec_fields(patch, holder, duration);
          int code = 200;
          std::string body;
          {
            std::lock_guard<std::mutex> lk(store.lease_mu);
            auto it = store.leases.find(lkey);
            if (it == store.leases.end()) {
              code = 404;
              body = "{\"kind\":\"Status\",\"code\":404}";
            } else {
              LeaseRec& L = it->second;
              double now = wall_unix_s();
              if (holder != L.holder && !lease_expired(L, now)) {
                // conflict-on-stolen-holder: both the standby's
                // premature grab and a revived zombie's stale renew
                code = 409;
                body =
                    "{\"kind\":\"Status\",\"apiVersion\":\"v1\","
                    "\"status\":\"Failure\",\"message\":\"lease \\\"";
                json_escape(body, lns);
                body += "/";
                json_escape(body, lname);
                body += "\\\" is held by \\\"";
                json_escape(body, L.holder);
                body +=
                    "\\\" and has not expired\",\"reason\":\"Conflict\","
                    "\"code\":409}";
              } else {
                std::string stamp = now_rfc3339();
                if (holder != L.holder) {
                  // expiry-acquire: leadership changes hands
                  L.holder = holder;
                  L.acquire = now;
                  L.acquire_str = stamp;
                  L.transitions++;
                }
                L.renew = now;
                L.renew_str = stamp;
                if (duration > 0) L.duration = duration;
                {
                  std::lock_guard<std::mutex> rk(store.mu);
                  L.rv = ++store.rv;
                }
                body = lease_render(lns, lname, L);
              }
            }
          }
          return respond(code, body);
        }
      }
      return respond(404, "{\"kind\":\"Status\",\"code\":404}");
    }
  }

  PathMatch m = match_path(req.path);
  if (m.binding && req.method != "POST")
    return respond(404, "{\"kind\":\"Status\",\"code\":404}");
  if (m.log && req.method != "GET")
    return respond(404, "{\"kind\":\"Status\",\"code\":404}");
  if (!m.ok || (req.method != "GET" && m.name.empty() && req.method != "POST"))
    return respond(404, "{\"kind\":\"Status\",\"code\":404}");

  // ---- server-side write fencing (ISSUE 12): a mutating request
  // carrying X-Kwok-Lease-Holder ("ns/name/holder") commits only while
  // that lease is currently held by that identity. The claim is parsed
  // here; fence_ok_locked() is evaluated as the FIRST statement inside
  // each mutation site's store-lock critical section — the same lock a
  // takeover PATCH serializes through, so check and commit are one
  // atomic step and a paused-and-revived zombie primary's in-flight
  // bytes die HERE no matter how the takeover interleaves. Requests
  // without the header pay one empty-string test (mirrors
  // mockserver._fenced_commit); the 409 is sent after the lock drops.
  bool fence_claimed =
      !req.lease_holder.empty() &&
      (req.method == "POST" || req.method == "PATCH" ||
       req.method == "DELETE");
  std::string fns, fname, fholder;
  if (fence_claimed) {
    const std::string& hdr = req.lease_holder;
    size_t f1 = hdr.find('/');
    size_t f2 = f1 == std::string::npos ? std::string::npos
                                        : hdr.find('/', f1 + 1);
    fns = f1 == std::string::npos ? "" : hdr.substr(0, f1);
    fname = f2 == std::string::npos ? "" : hdr.substr(f1 + 1, f2 - f1 - 1);
    fholder = f2 == std::string::npos ? "" : hdr.substr(f2 + 1);
  }
  // The fence guard (sharded edition of PR 12's single-critical-section
  // contract): lease_mu is taken BEFORE the shard lock and held across
  // the whole mutation (lease -> shard -> mu), so a takeover PATCH —
  // which serializes on lease_mu — can never interleave between the
  // claim check and the commit. Unfenced requests never touch it.
  auto fence_check = [&](std::unique_lock<std::mutex>& lk) {
    if (!fence_claimed) return true;
    lk = std::unique_lock<std::mutex>(store.lease_mu);
    if (fname.empty() || fholder.empty()) return false;
    auto it = store.leases.find(Key{fns, fname});
    return it != store.leases.end() && it->second.holder == fholder &&
           !lease_expired(it->second, wall_unix_s());
  };
  auto fencing_409 = [&]() {
    std::string body =
        "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
        "\"Failure\",\"message\":\"fencing lease ";
    json_escape(body, fns);
    body += "/";
    json_escape(body, fname);
    body += " is not held by ";
    json_escape(body, fholder);
    body += "\",\"reason\":\"Conflict\",\"code\":409}";
    return respond(409, body);
  };

  Key key{m.ns, m.name};

  if (m.log) {
    // GET pods/NAME/log on a kwok cluster: fake pods have no kubelet, so
    // the real apiserver's proxy to InternalIP:10250 fails and users see
    // the dial error as a 500 Status (mirrors mockserver.pod_log_status;
    // an unscheduled pod gets 400 'not have a host assigned').
    std::string node_name, container = q.count("container") ? q["container"] : "";
    bool found = false;
    std::string node_ip;
    {
      ShardPtr psh = store.shard_of(1, m.ns, /*create=*/false);
      EntryPtr pe;
      if (psh) {
        std::lock_guard<std::mutex> sl(psh->smu);
        auto it = psh->objs.find(m.name);
        if (it != psh->objs.end()) pe = it->second;
      }
      if (pe) {
        found = true;
        node_name = field_str(pe->obj, "spec.nodeName");
        if (container.empty()) {
          const JVal* spec = pe->obj.find("spec");
          const JVal* ctrs = spec && spec->is_obj() ? spec->find("containers") : nullptr;
          if (ctrs && ctrs->type == JVal::ARR && !ctrs->arr.empty())
            container = field_str(ctrs->arr[0], "name");
        }
      }
      if (!node_name.empty()) {
        node_ip = node_name;
        ShardPtr nsh = store.shard_of(0, "", /*create=*/false);
        EntryPtr ne;
        if (nsh) {
          std::lock_guard<std::mutex> sl(nsh->smu);
          auto nit = nsh->objs.find(node_name);
          if (nit != nsh->objs.end()) ne = nit->second;
        }
        if (ne) {
          const JVal* st = ne->obj.find("status");
          const JVal* addrs = st && st->is_obj() ? st->find("addresses") : nullptr;
          if (addrs && addrs->type == JVal::ARR)
            for (const JVal& a : addrs->arr)
              if (field_str(a, "type") == "InternalIP" &&
                  !field_str(a, "address").empty()) {
                node_ip = field_str(a, "address");
                break;
              }
        }
      }
    }
    pt.mark(PH_COMMIT);
    if (!found) {
      std::string body =
          "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":\"Failure\","
          "\"message\":\"pods \\\"";
      json_escape(body, m.name);
      body += "\\\" not found\",\"reason\":\"NotFound\",\"code\":404}";
      return respond(404, body);
    }
    if (node_name.empty()) {
      std::string body =
          "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":\"Failure\","
          "\"message\":\"pod ";
      json_escape(body, m.name);
      body += " does not have a host assigned\",\"reason\":\"BadRequest\","
              "\"code\":400}";
      return respond(400, body);
    }
    std::string url = "https://" + node_ip + ":10250/containerLogs/" + m.ns +
                      "/" + m.name + "/" + container;
    std::string msg = "Get \"" + url + "\": dial tcp " + node_ip +
                      ":10250: connect: connection refused";
    std::string body =
        "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":\"Failure\","
        "\"message\":\"";
    json_escape(body, msg);
    body += "\",\"code\":500}";
    return respond(500, body);
  }

  if (req.method == "GET") {
    if (!m.name.empty()) {
      // grab the entry ref under the SHARD lock, send outside it: a
      // stalled reader must never wedge the store (and a GET no longer
      // serializes against writers on other shards)
      EntryPtr e;
      ShardPtr sh = store.shard_of(m.kind, m.ns, /*create=*/false);
      if (sh) {
        std::lock_guard<std::mutex> sl(sh->smu);
        auto it = sh->objs.find(m.name);
        if (it != sh->objs.end()) e = it->second;
      }
      pt.mark(PH_COMMIT);
      if (!e) return respond(404, "{\"kind\":\"Status\",\"code\":404}");
      return respond(200, e->bytes);
    }
    std::string fs = q.count("fieldSelector") ? q["fieldSelector"] : "";
    std::string lsq = q.count("labelSelector") ? q["labelSelector"] : "";
    auto wq = q.find("watch");
    if (wq != q.end() && (wq->second == "true" || wq->second == "1")) {
      // ---- watch stream: headers now, then chunked events forever.
      // Responses to earlier pipelined requests must leave first — the
      // stream writes to the socket directly from here on.
      if (!io.flush()) return false;
      auto w = std::make_shared<Watch>();
      w->kind = m.kind;
      w->field_sel = fs;
      w->label_sel = LabelSel::parse(lsq);
      // request deadline (ListOptions.timeoutSeconds): the stream ends
      // CLEANLY (terminal chunk) at the first event boundary past it;
      // non-numeric values parse to 0 = no deadline (atof; the Python
      // mirror ignores unparseable values the same way)
      double timeout_s =
          q.count("timeoutSeconds") ? atof(q["timeoutSeconds"].c_str()) : 0;
      if (q.count("allowWatchBookmarks")) {
        const std::string& ab = q["allowWatchBookmarks"];
        w->bookmarks = (ab == "true" || ab == "1");
      }
      long long wrv = 0;
      if (q.count("resourceVersion")) {
        const std::string& rvs = q["resourceVersion"];
        if (rvs.find_first_not_of("0123456789") != std::string::npos)
          // non-numeric resourceVersion: 400, like the real apiserver
          // (and the Python mirror)
          return respond(
              400,
              "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
              "\"Failure\",\"message\":\"invalid resourceVersion\","
              "\"reason\":\"BadRequest\",\"code\":400}");
        wrv = atoll(rvs.c_str());
      }
      bool expired = false;
      long long too_large_current = -1;
      {
        std::lock_guard<std::mutex> lk(store.mu);
        if (wrv > 0) {
          if (wrv > store.rv) {
            // a resume AHEAD of the store (server restart reset the
            // revision clock): the real apiserver fails the handshake
            // with 504 "Too large resource version" + retry hint, NOT
            // 410 Expired (Python mirror: _too_large_rv_status). The
            // real watch cache blocks ~3s waiting to catch up first;
            // the mock answers immediately (documented divergence).
            too_large_current = store.rv;
          } else if (wrv < store.compacted_rv || rv_window() <= 0) {
            expired = true;
          } else {
            // replay the gap from the watch cache BEFORE registering:
            // commits hold mu too, so ordering is airtight. The replay
            // is exempt from the ring-lag cap (bounded by rv_window).
            for (const auto& h : store.history) {
              if (h.rv <= wrv || h.kind != m.kind) continue;
              if (!match_field_selector(h.e->obj, fs)) continue;
              if (!w->label_sel.matches(h.e->obj)) continue;
              w->replay.push_back(Store::event_line(h.type.c_str(), h.e));
            }
          }
        }
        if (!expired && too_large_current < 0) {
          // cursor starts at the ring head, atomically with the replay
          // collection: commits append under mu -> ring_mu, so holding
          // BOTH here means nothing falls between the cache gap and live
          std::lock_guard<std::mutex> rl(store.ring_mu);
          w->cursor = store.ring_next;
          w->created_unix = wall_unix_s();
          w->replay_pending.store((long)w->replay.size(),
                                  std::memory_order_relaxed);
          store.watches.push_back(w);
          store.kind_watchers[m.kind]++;
        }
      }
      if (too_large_current >= 0) {
        return respond(
            504,
            "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
            "\"Failure\",\"message\":\"Too large resource version: " +
                std::to_string(wrv) + ", current: " +
                std::to_string(too_large_current) +
                "\",\"reason\":\"Timeout\",\"details\":{\"causes\":[{"
                "\"reason\":\"ResourceVersionTooLarge\",\"message\":"
                "\"Too large resource version\"}],\"retryAfterSeconds\":1},"
                "\"code\":504}");
      }
      if (expired) {
        // the real apiserver answers an expired watch resume with 200 +
        // one ERROR event carrying a 410 Status, then closes the stream
        audit_line(req.method, uri, 200);
        std::string ev =
            "{\"type\":\"ERROR\",\"object\":{\"kind\":\"Status\","
            "\"apiVersion\":\"v1\",\"status\":\"Failure\","
            "\"message\":\"too old resource version: " +
            std::to_string(wrv) +
            "\",\"reason\":\"Expired\",\"code\":410}}\n";
        std::string head =
            "HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
            "Content-Length: " +
            std::to_string(ev.size()) + "\r\nConnection: close\r\n\r\n";
        head += ev;
        send_all(fd, head.data(), head.size());
        return false;
      }
      audit_line(req.method, uri, 200);
      const char* head =
          "HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
          "Transfer-Encoding: chunked\r\n\r\n";
      bool alive = send_all(fd, head, strlen(head));
      std::string out;
      auto frame = [&out](const std::string& ev) {
        char chunk_head[32];
        int hn = snprintf(chunk_head, sizeof chunk_head, "%zx\r\n",
                          ev.size());
        out.append(chunk_head, hn);
        out += ev;
        out += "\r\n";
      };
      // cap-exempt resume replay first (private to this watch; bounded
      // by rv_window), in bounded sends
      {
        size_t i = 0;
        while (alive && i < w->replay.size()) {
          out.clear();
          size_t take_bytes = 0;
          for (; i < w->replay.size() && take_bytes < (4u << 20); i++) {
            take_bytes += w->replay[i]->size();
            frame(*w->replay[i]);
          }
          alive = send_all(fd, out.data(), out.size());
        }
        w->replay.clear();
        w->replay_pending.store(0, std::memory_order_relaxed);
      }
      // Ring reader: drain everything pending per wakeup (bounded per
      // write) and ship it as one send. The store encoded each event
      // ONCE; this thread only filters and frames shared bytes — the
      // per-watcher cost left the commit path (ISSUE 13).
      std::vector<std::shared_ptr<const std::string>> evs;
      auto wdeadline =
          std::chrono::steady_clock::now() +
          std::chrono::duration_cast<std::chrono::steady_clock::duration>(
              std::chrono::duration<double>(timeout_s > 0 ? timeout_s : 0));
      bool deadline_expired = false;
      while (alive && !stopping.load()) {
        bool end_stream = false;
        evs.clear();
        {
          std::unique_lock<std::mutex> lk(store.ring_mu);
          auto ready = [&] {
            return w->closed || store.ring_next > w->cursor ||
                   stopping.load();
          };
          if (timeout_s > 0) {
            if (!store.ring_cv.wait_until(lk, wdeadline, ready)) {
              deadline_expired = true;
              break;
            }
            // the deadline closes at the next event BOUNDARY past it,
            // pending backlog or not (a flooding stream must not be
            // able to outrun its own timeoutSeconds)
            if (std::chrono::steady_clock::now() >= wdeadline) {
              deadline_expired = true;
              break;
            }
          } else {
            store.ring_cv.wait(lk, ready);
          }
          uint64_t base = store.ring_next - store.ring.size();
          if (w->cursor < base) w->cursor = base;  // trimmed past us
          uint64_t lim = store.ring_next;
          if (w->stop_seq < lim) lim = w->stop_seq;
          size_t take_bytes = 0;
          // cap the batch by BYTES, not events: one send buffer must
          // stay bounded even when large objects piled up
          while (w->cursor < lim && take_bytes < (4u << 20)) {
            const RingEv& ev = store.ring[w->cursor - base];
            w->cursor++;
            if (ev.kind != w->kind) continue;
            if (ev.bookmark) {
              if (!w->bookmarks) continue;
            } else if (!match_field_selector(ev.e->obj, w->field_sel) ||
                       !w->label_sel.matches(ev.e->obj)) {
              continue;
            }
            take_bytes += ev.line->size();
            evs.push_back(ev.line);
          }
          if (evs.empty() && w->closed && w->cursor >= lim)
            end_stream = true;
        }
        if (end_stream) break;  // slow close stays abrupt (backlog dropped)
        if (evs.empty()) continue;  // consumed only non-matching events
        out.clear();
        for (const auto& ev : evs) frame(*ev);
        alive = send_all(fd, out.data(), out.size());
      }
      if (alive && deadline_expired) {
        // timeoutSeconds expiry: END the watch cleanly (terminal chunk)
        // — the client resumes from its last revision. A slow-consumer
        // close stays abrupt (the backlog is already dropped).
        g_watch_term_deadline.fetch_add(1);
        send_all(fd, "0\r\n\r\n", 5);
      }
      {
        std::lock_guard<std::mutex> lk(store.ring_mu);
        store.close_watch_locked(w, /*slow=*/false);
        auto& ws = store.watches;
        ws.erase(std::remove(ws.begin(), ws.end(), w), ws.end());
        store.ring_min = 0;  // force a min-cursor recompute next trim
      }
      store.ring_cv.notify_all();
      return false;  // watch connections never go back to unary
    }
    // ---- list (with the kube-apiserver limit/continue chunking protocol)
    // Snapshot (key, entry) refs under the lock; match + serialize OUTSIDE
    // it. Writers only ever stall for the pointer copy, not for a
    // potentially-hundreds-of-MB response build.
    LabelSel ls = LabelSel::parse(lsq);
    long limit = q.count("limit") ? atol(q["limit"].c_str()) : 0;
    std::string cont = q.count("continue") ? q["continue"] : "";
    // Indexed count for the progress-poll shape (limit=N +
    // fieldSelector=status.phase=X, no label selector): the post-cut
    // remainder comes from phase_idx instead of matching every stored
    // object. -1 = no index applies; the slow scan is authoritative.
    // Resolved inside the snapshot's lock so count and snapshot agree.
    long idx_total = -1;
    std::string idx_phase;  // the selector's phase value when eligible
    bool idx_eligible = false;
    if (limit > 0 && cont.empty() && lsq.empty() &&
        fs.rfind("status.phase=", 0) == 0 && fs.find(',') == std::string::npos &&
        fs.find("!=") == std::string::npos) {
      idx_phase = fs.substr(13);
      if (!idx_phase.empty() && idx_phase[0] == '=')
        idx_phase.erase(0, 1);  // the '==' dialect match_field_selector takes
      // any further '=' or whitespace means a dialect the exact-key index
      // cannot answer — leave it to the authoritative scan
      idx_eligible = !idx_phase.empty() &&
                     idx_phase.find_first_of("= \t") == std::string::npos;
    }
    // Continuation pages snapshot a BOUNDED slice (each page must be O(page)
    // lock work, or a full paginated re-list at 1M objects goes quadratic in
    // pointer copies); a short page with a continue token is protocol-legal,
    // so heavy selector filtering just yields more, cheaper pages. First
    // pages (which report remainingItemCount) snapshot everything.
    bool count_rest = cont.empty();
    size_t snap_cap = count_rest
                          ? (size_t)-1
                          : (size_t)std::max(limit * 4L, 4096L);
    int64_t rv_now = 0;
    int64_t token_rv = 0;  // consistency marker: rv of the FIRST page
    Key last{"", ""};
    bool have_last = false;
    if (!cont.empty()) {
      // opaque url-safe token (like the real apiserver's base64
      // continue): rv \0 ns \0 name — resumes strictly after the key;
      // the rv is the first page's revision and expires on compaction
      std::string raw;
      size_t p1;
      if (!b64url_decode(cont, raw) ||
          (p1 = raw.find('\0')) == std::string::npos || p1 == 0 ||
          raw.find_first_not_of("0123456789") < p1)
        // undecodable token OR a non-numeric rv segment: 400, like the
        // real apiserver's "continue key is not valid" (and the Python
        // mirror's MalformedContinue)
        return respond(
            400,
            "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
            "\"Failure\",\"message\":\"continue key is not valid\","
            "\"reason\":\"BadRequest\",\"code\":400}");
      token_rv = atoll(raw.substr(0, p1).c_str());
      std::string rest = raw.substr(p1 + 1);
      size_t nul = rest.find('\0');
      last = Key{rest.substr(0, nul),
                 nul == std::string::npos ? "" : rest.substr(nul + 1)};
      have_last = true;
    }
    // EVERY page — first or continuation — serves a CONSISTENT SNAPSHOT
    // at one revision (what the real apiserver reads from etcd MVCC):
    // the sharded store is walked shard by shard (shard locks never
    // nest) and rolled back through the undo log to the list revision,
    // so a write racing the walk on another shard can neither leak in
    // nor hide. Newest-to-oldest overlay walk, so the final value for a
    // key is the prev of its EARLIEST post-revision event — exactly its
    // state at the list revision (nullptr = absent then). rv_window()==0
    // disables the cache and keeps the live-view behavior.
    std::vector<std::pair<Key, EntryPtr>> snap;
    bool more_after = false;
    std::map<Key, EntryPtr> overlay;
    for (int attempt = 0; attempt < 4; attempt++) {
      {
        std::lock_guard<std::mutex> lk(store.mu);
        if (have_last) {
          if (token_rv < store.compacted_rv)
            return respond(
                410,
                "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
                "\"Failure\",\"message\":\"the provided continue parameter "
                "is too old\",\"reason\":\"Expired\",\"code\":410}");
          rv_now = token_rv;  // pages of one list share page 1's revision
        } else {
          rv_now = store.rv;
          token_rv = rv_now;  // first page stamps its revision
          if (idx_eligible) {
            auto pit = store.phase_idx[m.kind].find(idx_phase);
            idx_total =
                pit == store.phase_idx[m.kind].end() ? 0 : pit->second;
          } else if (limit > 0 && lsq.empty() && fs.empty()) {
            // selector-less count (limit=1 population polls): every
            // stored entry matches, so the population count IS the total
            // (kept under mu with rv, so count and revision agree)
            idx_total = store.obj_count[m.kind];
          }
        }
      }
      snap.clear();
      more_after = false;
      for (auto& ns_sh : store.kind_shards(m.kind)) {
        if (have_last && ns_sh.first < last.first) continue;
        std::lock_guard<std::mutex> sl(ns_sh.second->smu);
        auto it = ns_sh.second->objs.begin();
        if (have_last && ns_sh.first == last.first)
          it = ns_sh.second->objs.upper_bound(last.second);
        for (; it != ns_sh.second->objs.end(); ++it) {
          if (snap.size() >= snap_cap) {
            more_after = true;
            break;
          }
          snap.emplace_back(Key{ns_sh.first, it->first}, it->second);
        }
        if (more_after) break;
      }
      {
        std::lock_guard<std::mutex> lk(store.mu);
        if (rv_window() > 0 && rv_now < store.compacted_rv) {
          if (have_last)
            return respond(
                410,
                "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
                "\"Failure\",\"message\":\"the provided continue "
                "parameter is too old\",\"reason\":\"Expired\","
                "\"code\":410}");
          if (attempt < 3) continue;  // compaction raced the walk: retry
          overlay.clear();  // repeated compactions: serve the live walk
          break;
        }
        overlay.clear();
        for (auto u = store.undo.rbegin(); u != store.undo.rend(); ++u) {
          if (u->rv <= rv_now) break;
          if (u->kind != m.kind) continue;
          if (have_last && !(last < u->key)) continue;
          overlay[u->key] = u->prev;
        }
      }
      break;
    }
    // a truncated walk must not let overlay keys past the cut fabricate
    // out-of-order entries — the continuation resumes there instead
    if (more_after && !snap.empty()) {
      Key cut = snap.back().first;
      while (!overlay.empty() && cut < overlay.rbegin()->first)
        overlay.erase(std::prev(overlay.end()));
    }
    // merged view: walk snapshot + rollback overlay (both key-sorted);
    // the overlay's state wins where both hold a key
    std::vector<EntryPtr> view;
    {
      auto sit = snap.begin();
      auto ov = overlay.begin();
      while (sit != snap.end() || ov != overlay.end()) {
        bool use_ov;
        if (ov == overlay.end()) use_ov = false;
        else if (sit == snap.end()) use_ov = true;
        else if (ov->first < sit->first) use_ov = true;
        else if (sit->first < ov->first) use_ov = false;
        else {  // same key: the rolled-back state wins over the live one
          use_ov = true;
          ++sit;
        }
        EntryPtr e;
        if (use_ov) {
          e = ov->second;
          ++ov;
        } else {
          e = sit->second;
          ++sit;
        }
        if (!e) continue;  // hidden at the view revision (created later)
        if (view.size() >= snap_cap) {
          // only a VISIBLE leftover earns a continue token: keys hidden
          // by the snapshot must not fabricate a trailing empty page
          more_after = true;
          break;
        }
        view.push_back(std::move(e));
      }
    }
    pt.mark(PH_COMMIT);  // snapshot under the locks; match/serialize below
                         // is response build, attributed to encode
    // The continue token is rebuilt from the entry's own (immutable)
    // metadata — map keys may be erased concurrently once the lock drops.
    auto key_of = [token_rv](const JVal& obj, std::string& out) {
      const JVal* meta = obj.find("metadata");
      const JVal* ns = meta ? meta->find("namespace") : nullptr;
      const JVal* name = meta ? meta->find("name") : nullptr;
      std::string raw = std::to_string(token_rv);
      raw += '\0';
      if (ns && ns->type == JVal::STR) raw += ns->s;
      raw += '\0';
      if (name && name->type == JVal::STR) raw += name->s;
      out = b64url_encode(raw);
    };
    // Continuation pages break at the cut (counting the remainder on every
    // page would make a full re-list quadratic); only the FIRST page scans
    // on for ListMeta.remainingItemCount, which is what limit=1 count
    // pollers read.
    std::string items;
    std::string token;
    long count = 0;
    long remaining = 0;
    bool first = true;
    for (size_t i = 0; i < view.size(); i++) {
      const JVal& obj = view[i]->obj;
      // the index knows no further entry can match: stop scanning (a
      // zero-match poll — e.g. phase=Running before any transition —
      // would otherwise walk the whole store)
      if (idx_total >= 0 && count >= idx_total) break;
      if (limit && count >= limit) {
        if (!count_rest) break;  // continuation pages stop at the cut
        if (idx_total >= 0) {
          // indexed remainder: total matches minus those already emitted
          remaining = std::max(0L, idx_total - count);
          break;
        }
        if (!match_field_selector(obj, fs)) continue;
        if (!ls.matches(obj)) continue;
        remaining++;
        continue;
      }
      if (!match_field_selector(obj, fs)) continue;
      if (!ls.matches(obj)) continue;
      if (!first) items += ',';
      first = false;
      items += view[i]->bytes;
      count++;
      if (limit && count >= limit && (i + 1 < view.size() || more_after))
        key_of(obj, token);
    }
    if (limit && !count_rest && token.empty() && more_after && !view.empty())
      // truncated snapshot, page not filled: continue from the last entry
      // we actually examined (a short page; the client keeps paginating)
      key_of(view.back()->obj, token);
    std::string body =
        "{\"kind\":\"List\",\"apiVersion\":\"v1\",\"metadata\":{"
        "\"resourceVersion\":\"";
    body += std::to_string(rv_now);
    body += '"';
    // first pages gate the token on a known matching remainder; later
    // pages emit it whenever entries remain (an empty final page is fine)
    if (!token.empty() && (count_rest ? remaining > 0 : true)) {
      body += ",\"continue\":\"";
      json_escape(body, token);
      body += '"';
    }
    if (limit && count_rest && remaining > 0) {
      // ListMeta.remainingItemCount: lets pollers count a population with
      // limit=1 instead of transferring the whole serialized list
      body += ",\"remainingItemCount\":";
      body += std::to_string(remaining);
    }
    body += "},\"items\":[";
    body += items;
    body += "]}";
    return respond(200, body);
  }

  if (req.method == "POST" && m.binding) {
    // the real scheduler's bind: POST v1 Binding -> set spec.nodeName once
    JParser p(req.body);
    JVal b = p.parse();
    pt.mark(PH_PARSE);
    if (p.ok) pt.parsed = true;
    const JVal* target = b.is_obj() ? b.find("target") : nullptr;
    const JVal* tname =
        target && target->is_obj() ? target->find("name") : nullptr;
    std::string node = tname && tname->type == JVal::STR ? tname->s : "";
    std::string conflict;
    bool found = false;
    bool fenced = false;
    bool committed = false;
    {
      std::unique_lock<std::mutex> fence_lk;
      if (!fence_check(fence_lk)) {
        fenced = true;  // check+commit atomic: respond after the locks
      } else {
        ShardPtr sh = store.shard_of(1, m.ns, /*create=*/false);
        if (sh) {
          std::lock_guard<std::mutex> sl(sh->smu);
          auto it = sh->objs.find(m.name);
          if (it != sh->objs.end()) {
            found = true;
            JVal obj = it->second->obj;  // copy-on-write
            JVal& spec = obj.get_or_insert_obj("spec");
            const JVal* cur = spec.find("nodeName");
            if (cur && cur->type == JVal::STR && !cur->s.empty()) {
              // real apiserver BindingREST: any bind after spec.nodeName
              // is set conflicts, even to the same node
              conflict = cur->s;
            } else {
              spec.set("nodeName", JVal::str(node));
              EntryPtr prev = it->second;
              std::lock_guard<std::mutex> lk(store.mu);
              it->second = store.commit_locked(
                  1, "MODIFIED", std::move(obj), key, std::move(prev),
                  pt.on ? &pt.us[PH_FANOUT] : nullptr, sh.get());
              committed = true;
            }
          }
        }
      }
    }
    wake_ring = committed;
    pt.mark(PH_COMMIT);
    if (fenced) return fencing_409();
    if (!found) return respond(404, "{\"kind\":\"Status\",\"code\":404}");
    if (!conflict.empty()) {
      std::string body =
          "{\"kind\":\"Status\",\"status\":\"Failure\",\"reason\":"
          "\"Conflict\",\"message\":\"pod ";
      json_escape(body, m.name);
      body += " is already assigned to node ";
      json_escape(body, conflict);
      body += "\",\"code\":409}";
      return respond(409, body);
    }
    return respond(
        201, "{\"kind\":\"Status\",\"status\":\"Success\",\"code\":201}");
  }

  if (req.method == "POST") {
    if (!m.name.empty() || m.status)
      return respond(404, "{\"kind\":\"Status\",\"code\":404}");
    JParser p(req.body);
    JVal obj = p.parse();
    pt.mark(PH_PARSE);
    if (p.ok) pt.parsed = true;
    if (!p.ok || obj.type != JVal::OBJ)
      return respond(400, "{\"kind\":\"Status\",\"code\":400}");
    JVal& meta = obj.get_or_insert_obj("metadata");
    if (!m.ns.empty()) meta.set("namespace", JVal::str(m.ns));
    EntryPtr e;
    std::string exists_name;
    bool fenced = false;
    bool committed = false;
    {
      std::unique_lock<std::mutex> fence_lk;
      if (!fence_check(fence_lk)) {
        // check+commit atomic: fenced requests skip the whole mutation
        // and answer after the locks drop
        fenced = true;
      } else {
        ShardPtr sh = store.shard_of(m.kind, m.ns);
        std::lock_guard<std::mutex> sl(sh->smu);
        if (!meta.find("name")) {
          // apiserver names.go semantics: generateName + 5-char random
          // suffix (kube-scheduler POSTs events this way). Resolved
          // inside the shard's critical section — the name stays unique
          // through the insert, never silently overwriting an existing
          // object (the real apiserver 409s and the client retries).
          const JVal* gn = meta.find("generateName");
          if (gn && gn->type == JVal::STR && !gn->s.empty()) {
            static const char hexd[] = "0123456789abcdef";
            static std::atomic<uint64_t> ctr{0};
            while (true) {
              uint64_t x = (uint64_t)time(nullptr) * 1000003u +
                           ctr.fetch_add(1) * 2654435761u;
              std::string suffix;
              for (int i = 0; i < 5; i++) {
                suffix += hexd[x & 15];
                x >>= 4;
              }
              std::string name = gn->s + suffix;
              if (!sh->objs.count(name)) {
                meta.set("name", JVal::str(name));
                break;
              }
            }
          }
        }
        Key k = Store::obj_key(obj);
        if (k.second.empty()) {
          e = nullptr;
        } else if (sh->objs.count(k.second)) {
          // the real apiserver never overwrites on create (HTTP 409;
          // mirrors mockserver.py AlreadyExists). Respond AFTER the
          // locks drop (a stalled client must not wedge the store).
          exists_name = k.second;
          e = nullptr;
        } else {
          if (!meta.find("creationTimestamp"))
            meta.set("creationTimestamp", JVal::str(now_rfc3339()));
          std::lock_guard<std::mutex> lk(store.mu);
          e = store.commit_locked(m.kind, "ADDED", std::move(obj), k,
                                  nullptr,
                                  pt.on ? &pt.us[PH_FANOUT] : nullptr,
                                  sh.get(), /*stamp_uid=*/true);
          sh->objs[k.second] = e;
          committed = true;
        }
      }
    }
    wake_ring = committed;
    if (committed && m.kind == kind_index("events"))
      evict_events(pt.on ? &pt.us[PH_FANOUT] : nullptr);
    pt.mark(PH_COMMIT);
    if (fenced) return fencing_409();
    if (!exists_name.empty()) {
      std::string body =
          "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
          "\"Failure\",\"message\":\"";
      json_escape(body, KIND_NAMES[m.kind]);
      body += " \\\"";
      json_escape(body, exists_name);
      body +=
          "\\\" already exists\",\"reason\":\"AlreadyExists\","
          "\"code\":409}";
      return respond(409, body);
    }
    if (!e) return respond(400, "{\"kind\":\"Status\",\"code\":400}");
    return respond(201, e->bytes);
  }

  if (req.method == "PATCH") {
    JParser p(req.body);
    JVal patch = p.parse();
    pt.mark(PH_PARSE);
    if (p.ok) pt.parsed = true;
    if (!p.ok) return respond(400, "{\"kind\":\"Status\",\"code\":400}");
    std::string body;
    int code = 200;
    bool fenced = false;
    bool committed = false;
    {
      std::unique_lock<std::mutex> fence_lk;
      if (!fence_check(fence_lk)) {
        fenced = true;  // check+commit atomic: respond after the locks
      } else {
        ShardPtr sh = store.shard_of(m.kind, m.ns, /*create=*/false);
        bool found = false;
        if (sh) {
          std::lock_guard<std::mutex> sl(sh->smu);
          auto it = sh->objs.find(m.name);
          if (it != sh->objs.end()) {
            found = true;
            JVal obj = it->second->obj;  // copy-on-write
            if (m.status) {
              // strategic-merge on the status subresource; accept
              // either a {"status": {...}} wrapper or a bare status
              // document
              const JVal* sp =
                  patch.is_obj() ? patch.find("status") : nullptr;
              const JVal& spv = sp ? *sp : patch;
              JVal cur_status;
              cur_status.type = JVal::OBJ;
              if (const JVal* cs = obj.find("status"))
                if (cs->type == JVal::OBJ) cur_status = *cs;
              obj.set("status", merge_value(cur_status, spv, ""));
            } else {
              // merge-patch on metadata + spec with null deletion;
              // top-level key replace within each section
              // (mockserver.patch_meta)
              for (const char* section : {"metadata", "spec"}) {
                const JVal* sec_patch =
                    patch.is_obj() ? patch.find(section) : nullptr;
                if (!sec_patch || sec_patch->type != JVal::OBJ ||
                    sec_patch->obj.empty())
                  continue;
                JVal& sec = obj.get_or_insert_obj(section);
                for (const auto& kv : sec_patch->obj) {
                  if (kv.second.type == JVal::NUL) sec.erase(kv.first);
                  else sec.set(kv.first, kv.second);
                }
              }
            }
            EntryPtr prev = it->second;
            std::lock_guard<std::mutex> lk(store.mu);
            EntryPtr e = store.commit_locked(
                m.kind, "MODIFIED", std::move(obj), key, std::move(prev),
                pt.on ? &pt.us[PH_FANOUT] : nullptr, sh.get());
            it->second = e;
            body = e->bytes;
            committed = true;
          }
        }
        if (!found) {
          code = 404;
          body = "{\"kind\":\"Status\",\"code\":404}";
        }
      }
    }
    wake_ring = committed;
    pt.mark(PH_COMMIT);
    if (fenced) return fencing_409();
    return respond(code, body);
  }

  if (req.method == "DELETE") {
    long grace = 0;
    bool grace_given = false;
    if (!req.body.empty()) {
      JParser p(req.body);
      JVal b = p.parse();
      pt.mark(PH_PARSE);
      if (p.ok) pt.parsed = true;
      const JVal* g = b.is_obj() ? b.find("gracePeriodSeconds") : nullptr;
      if (g && g->type == JVal::NUM) {
        grace = atol(g->s.c_str());
        grace_given = true;
      }
    }
    bool fenced = false;
    bool committed = false;
    {
      std::unique_lock<std::mutex> fence_lk;
      if (!fence_check(fence_lk)) {
        fenced = true;  // check+commit atomic: respond after the locks
      } else {
        ShardPtr sh = store.shard_of(m.kind, m.ns, /*create=*/false);
        if (sh) {
          std::lock_guard<std::mutex> sl(sh->smu);
          auto it = sh->objs.find(m.name);
          if (it != sh->objs.end()) {
            JVal obj = it->second->obj;  // copy-on-write
            if (!grace_given && m.kind == 1) {
              // DeleteOptions omitted: server default for pods is
              // spec.terminationGracePeriodSeconds or 30 (mirrors
              // mockserver.py FakeKube.delete)
              grace = 30;
              const JVal* spec = obj.find("spec");
              const JVal* tg =
                  spec && spec->is_obj()
                      ? spec->find("terminationGracePeriodSeconds")
                      : nullptr;
              if (tg && tg->type == JVal::NUM) grace = atol(tg->s.c_str());
            }
            JVal& meta = obj.get_or_insert_obj("metadata");
            const JVal* fins = meta.find("finalizers");
            bool has_fins =
                fins && fins->type == JVal::ARR && !fins->arr.empty();
            if (m.kind == 1 && (grace > 0 || has_fins)) {
              // graceful: mark, wait for the kubelet (engine) to
              // force-delete
              if (!meta.find("deletionTimestamp"))
                meta.set("deletionTimestamp", JVal::str(now_rfc3339()));
              meta.set("deletionGracePeriodSeconds",
                       JVal::num_raw(std::to_string(grace)));
              EntryPtr prev = it->second;
              std::lock_guard<std::mutex> lk(store.mu);
              it->second = store.commit_locked(
                  m.kind, "MODIFIED", std::move(obj), key,
                  std::move(prev), pt.on ? &pt.us[PH_FANOUT] : nullptr,
                  sh.get());
            } else {
              EntryPtr prev = it->second;
              sh->objs.erase(it);
              std::lock_guard<std::mutex> lk(store.mu);
              store.commit_locked(
                  m.kind, "DELETED", std::move(obj), key,
                  std::move(prev), pt.on ? &pt.us[PH_FANOUT] : nullptr,
                  sh.get());
            }
            committed = true;
          }
        }
      }
    }
    wake_ring = committed;
    pt.mark(PH_COMMIT);
    if (fenced) return fencing_409();
    return respond(200, "{\"kind\":\"Status\",\"status\":\"Success\"}");
  }

  return respond(404, "{\"kind\":\"Status\",\"code\":404}");
}

// One request's timing close-out for the batched write path (mutating
// verbs only — never a watch shape). A batched item's phases are its
// OWN work slices (pt.last is re-baselined between the transaction's
// phases), so its "total" is the sum of those slices — the request's
// server-side processing time, excluding the queueing behind its
// batch-mates, exactly as the unary pipelined path excludes the
// queueing behind earlier requests by stamping t_start at pick-up.
static void finish_write_timing(const Request& req, PhaseTimer& pt,
                                int code, const std::string& uri) {
  if (!req.t_start) return;
  pt.mark(PH_ENCODE);
  uint64_t t0 = req.t_start;
  uint64_t t_hdr = req.t_hdr ? req.t_hdr : t0;
  uint64_t t_body = req.t_body ? req.t_body : t_hdr;
  pt.us[PH_READ_HEADERS] = (double)(t_hdr - t0) / 1000.0;
  pt.us[PH_READ_BODY] = (double)(t_body - t_hdr) / 1000.0;
  double total_us = pt.us[PH_READ_HEADERS] + pt.us[PH_READ_BODY] +
                    pt.us[PH_PARSE] + pt.us[PH_COMMIT] + pt.us[PH_ENCODE];
  uint64_t t_end = t0 + (uint64_t)(total_us * 1000.0);
  g_phase_hist[PH_READ_HEADERS].observe_ns(t_hdr - t0);
  g_phase_hist[PH_READ_BODY].observe_ns(t_body - t_hdr);
  g_phase_hist[PH_COMMIT].observe_ns((uint64_t)(pt.us[PH_COMMIT] * 1000.0));
  g_phase_hist[PH_ENCODE].observe_ns((uint64_t)(pt.us[PH_ENCODE] * 1000.0));
  if (pt.parsed)
    g_phase_hist[PH_PARSE].observe_ns((uint64_t)(pt.us[PH_PARSE] * 1000.0));
  if (pt.us[PH_FANOUT] > 0)
    g_phase_hist[PH_FANOUT].observe_ns(
        (uint64_t)(pt.us[PH_FANOUT] * 1000.0));
  int vi = 5;
  if (req.method == "POST") vi = 2;
  else if (req.method == "PATCH") vi = 3;
  else if (req.method == "DELETE") vi = 4;
  g_verb_hist[vi].observe_ns(t_end - t0);
  FlightRec rec;
  rec.method = req.method;
  rec.path = uri;
  rec.status = code;
  rec.band = "mutating";  // batchable shapes are all mutating verbs
  rec.ts_unix = wall_unix_s() - total_us / 1e6;
  rec.total_us = total_us;
  for (int p = 0; p < N_PHASES; p++) rec.phases_us[p] = pt.us[p];
  flight_record(std::move(rec));
}

// Applies ONE batchable write with the owning shard's smu AND store.mu
// held by the caller (the batched transaction holds them once per
// consecutive same-shard run). Mirrors handle_request's unary verbs —
// the batched-write parity twin pins the rv sequence and response bytes
// against the Python server, which processes the same pipelined batch
// request-by-request. Returns whether an event committed.
static bool apply_write_locked(Store& store, Shard& sh, const PathMatch& m,
                               const Request& req, JVal& body,
                               bool parse_ok, PhaseTimer& pt, int* code,
                               std::string* resp, bool* need_evict) {
  double* fan = pt.on ? &pt.us[PH_FANOUT] : nullptr;
  Key key{m.ns, m.name};
  if (req.method == "POST" && m.binding) {
    const JVal* target = body.is_obj() ? body.find("target") : nullptr;
    const JVal* tname =
        target && target->is_obj() ? target->find("name") : nullptr;
    std::string node = tname && tname->type == JVal::STR ? tname->s : "";
    auto it = sh.objs.find(m.name);
    if (it == sh.objs.end()) {
      *code = 404;
      *resp = "{\"kind\":\"Status\",\"code\":404}";
      return false;
    }
    JVal obj = it->second->obj;  // copy-on-write
    JVal& spec = obj.get_or_insert_obj("spec");
    const JVal* cur = spec.find("nodeName");
    if (cur && cur->type == JVal::STR && !cur->s.empty()) {
      *code = 409;
      std::string b =
          "{\"kind\":\"Status\",\"status\":\"Failure\",\"reason\":"
          "\"Conflict\",\"message\":\"pod ";
      json_escape(b, m.name);
      b += " is already assigned to node ";
      json_escape(b, cur->s);
      b += "\",\"code\":409}";
      *resp = std::move(b);
      return false;
    }
    spec.set("nodeName", JVal::str(node));
    EntryPtr prev = it->second;
    it->second = store.commit_locked(1, "MODIFIED", std::move(obj), key,
                                     std::move(prev), fan, &sh);
    *code = 201;
    *resp = "{\"kind\":\"Status\",\"status\":\"Success\",\"code\":201}";
    return true;
  }
  if (req.method == "POST") {
    if (!parse_ok || body.type != JVal::OBJ) {
      *code = 400;
      *resp = "{\"kind\":\"Status\",\"code\":400}";
      return false;
    }
    JVal obj = std::move(body);
    JVal& meta = obj.get_or_insert_obj("metadata");
    if (!m.ns.empty()) meta.set("namespace", JVal::str(m.ns));
    if (!meta.find("name")) {
      const JVal* gn = meta.find("generateName");
      if (gn && gn->type == JVal::STR && !gn->s.empty()) {
        static const char hexd[] = "0123456789abcdef";
        static std::atomic<uint64_t> ctr{0};
        while (true) {
          uint64_t x = (uint64_t)time(nullptr) * 1000003u +
                       ctr.fetch_add(1) * 2654435761u;
          std::string suffix;
          for (int i = 0; i < 5; i++) {
            suffix += hexd[x & 15];
            x >>= 4;
          }
          std::string name = gn->s + suffix;
          if (!sh.objs.count(name)) {
            meta.set("name", JVal::str(name));
            break;
          }
        }
      }
    }
    Key k = Store::obj_key(obj);
    if (k.second.empty()) {
      *code = 400;
      *resp = "{\"kind\":\"Status\",\"code\":400}";
      return false;
    }
    if (sh.objs.count(k.second)) {
      *code = 409;
      std::string b =
          "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
          "\"Failure\",\"message\":\"";
      json_escape(b, KIND_NAMES[m.kind]);
      b += " \\\"";
      json_escape(b, k.second);
      b += "\\\" already exists\",\"reason\":\"AlreadyExists\","
           "\"code\":409}";
      *resp = std::move(b);
      return false;
    }
    if (!meta.find("creationTimestamp"))
      meta.set("creationTimestamp", JVal::str(now_rfc3339()));
    EntryPtr e = store.commit_locked(m.kind, "ADDED", std::move(obj), k,
                                     nullptr, fan, &sh,
                                     /*stamp_uid=*/true);
    sh.objs[k.second] = e;
    *code = 201;
    *resp = e->bytes;
    if (m.kind == kind_index("events")) *need_evict = true;
    return true;
  }
  if (req.method == "PATCH") {
    if (!parse_ok) {
      *code = 400;
      *resp = "{\"kind\":\"Status\",\"code\":400}";
      return false;
    }
    auto it = sh.objs.find(m.name);
    if (it == sh.objs.end()) {
      *code = 404;
      *resp = "{\"kind\":\"Status\",\"code\":404}";
      return false;
    }
    JVal obj = it->second->obj;  // copy-on-write
    if (m.status) {
      const JVal* sp = body.is_obj() ? body.find("status") : nullptr;
      const JVal& spv = sp ? *sp : body;
      JVal cur_status;
      cur_status.type = JVal::OBJ;
      if (const JVal* cs = obj.find("status"))
        if (cs->type == JVal::OBJ) cur_status = *cs;
      obj.set("status", merge_value(cur_status, spv, ""));
    } else {
      for (const char* section : {"metadata", "spec"}) {
        const JVal* sec_patch =
            body.is_obj() ? body.find(section) : nullptr;
        if (!sec_patch || sec_patch->type != JVal::OBJ ||
            sec_patch->obj.empty())
          continue;
        JVal& sec = obj.get_or_insert_obj(section);
        for (const auto& kv : sec_patch->obj) {
          if (kv.second.type == JVal::NUL) sec.erase(kv.first);
          else sec.set(kv.first, kv.second);
        }
      }
    }
    EntryPtr prev = it->second;
    EntryPtr e = store.commit_locked(m.kind, "MODIFIED", std::move(obj),
                                     key, std::move(prev), fan, &sh);
    it->second = e;
    *code = 200;
    *resp = e->bytes;
    return true;
  }
  // DELETE
  long grace = 0;
  bool grace_given = false;
  const JVal* g = body.is_obj() ? body.find("gracePeriodSeconds") : nullptr;
  if (g && g->type == JVal::NUM) {
    grace = atol(g->s.c_str());
    grace_given = true;
  }
  bool committed = false;
  auto it = sh.objs.find(m.name);
  if (it != sh.objs.end()) {
    JVal obj = it->second->obj;  // copy-on-write
    if (!grace_given && m.kind == 1) {
      grace = 30;
      const JVal* spec = obj.find("spec");
      const JVal* tg = spec && spec->is_obj()
                           ? spec->find("terminationGracePeriodSeconds")
                           : nullptr;
      if (tg && tg->type == JVal::NUM) grace = atol(tg->s.c_str());
    }
    JVal& meta = obj.get_or_insert_obj("metadata");
    const JVal* fins = meta.find("finalizers");
    bool has_fins = fins && fins->type == JVal::ARR && !fins->arr.empty();
    if (m.kind == 1 && (grace > 0 || has_fins)) {
      if (!meta.find("deletionTimestamp"))
        meta.set("deletionTimestamp", JVal::str(now_rfc3339()));
      meta.set("deletionGracePeriodSeconds",
               JVal::num_raw(std::to_string(grace)));
      EntryPtr prev = it->second;
      it->second = store.commit_locked(m.kind, "MODIFIED", std::move(obj),
                                       key, std::move(prev), fan, &sh);
    } else {
      EntryPtr prev = it->second;
      sh.objs.erase(it);
      store.commit_locked(m.kind, "DELETED", std::move(obj), key,
                          std::move(prev), fan, &sh);
    }
    committed = true;
  }
  *code = 200;
  *resp = "{\"kind\":\"Status\",\"status\":\"Success\"}";
  return committed;
}

// The batched write transaction (ISSUE 13): N creates/binds/status-
// patches that arrived in one socket read (the native pump pipelines
// whole frames) execute as consecutive same-shard runs, each under ONE
// shard-lock + ONE clock-lock hold, with ONE rv allocation run, one
// ring append per event and a single watcher wake for the whole batch —
// instead of N lock/notify round-trips. Admission still answers 429 per
// request; responses/audit/timing are per request, in arrival order.
size_t App::exec_write_batch(ConnIO& io, std::vector<Request>& batch) {
  struct Item {
    PathMatch m;
    JVal body;
    bool parse_ok = false;
    PhaseTimer pt;
    int code = 0;
    std::string resp;
    bool unauthorized = false;
    bool rejected = false;  // admission 429
    bool need_evict = false;
  };
  std::vector<Item> items(batch.size());
  // phase 1: auth + body parse, no locks (admission is taken per item
  // in phase 2 — one slot at a time, like the sequential unary path)
  for (size_t i = 0; i < batch.size(); i++) {
    Request& rq = batch[i];
    Item& it = items[i];
    it.m = match_path(rq.path);
    if (rq.t_start) {
      it.pt.on = true;
      it.pt.last = rq.t_body ? rq.t_body : now_ns();
    }
    if (!auth_tokens.empty() &&
        (rq.auth.rfind("Bearer ", 0) != 0 ||
         !auth_tokens.count(rq.auth.substr(7)))) {
      it.unauthorized = true;
      it.code = 401;
      it.resp =
          "{\"kind\":\"Status\",\"apiVersion\":\"v1\",\"status\":"
          "\"Failure\",\"reason\":\"Unauthorized\",\"message\":"
          "\"Unauthorized\",\"code\":401}";
      continue;
    }
    if (it.pt.on) it.pt.last = now_ns();  // re-baseline: own parse slice
    JParser p(rq.body);
    it.body = p.parse();
    it.pt.mark(PH_PARSE);
    if (p.ok) {
      it.parse_ok = true;
      it.pt.parsed = true;
    }
  }
  // phase 2: the store transaction — consecutive same-(kind, ns) runs
  // under one shard+clock hold; one ring wake for the whole batch
  bool committed_any = false;
  bool any_evict = false;
  size_t i = 0;
  while (i < batch.size()) {
    if (items[i].unauthorized || items[i].rejected) {
      i++;
      continue;
    }
    size_t j = i + 1;
    while (j < batch.size() && !items[j].unauthorized &&
           !items[j].rejected && items[j].m.kind == items[i].m.kind &&
           items[j].m.ns == items[i].m.ns)
      j++;
    ShardPtr sh = store.shard_of(items[i].m.kind, items[i].m.ns);
    {
      std::lock_guard<std::mutex> sl(sh->smu);
      std::lock_guard<std::mutex> lk(store.mu);
      for (size_t k2 = i; k2 < j; k2++) {
        Item& it = items[k2];
        // admission: one slot held per ITEM, acquired and released in
        // sequence — a connection's own pipelined burst must not
        // self-saturate the mutating band (the unary path, and the
        // Python twin working through the same bytes, only ever hold
        // one slot per connection at a time)
        if (max_inflight_band[1] > 0) {
          if (inflight[1].fetch_add(1) + 1 > max_inflight_band[1]) {
            inflight[1].fetch_sub(1);
            rejected[1].fetch_add(1);
            it.rejected = true;
            it.code = 429;
            it.resp = TOO_MANY_REQUESTS_BODY;
            continue;
          }
        }
        // re-baseline: the commit phase is THIS item's store work, not
        // the wait behind its batch-mates (see finish_write_timing)
        if (it.pt.on) it.pt.last = now_ns();
        if (apply_write_locked(store, *sh, it.m, batch[k2], it.body,
                               it.parse_ok, it.pt, &it.code, &it.resp,
                               &it.need_evict))
          committed_any = true;
        it.pt.mark(PH_COMMIT);
        if (it.need_evict) any_evict = true;
        if (max_inflight_band[1] > 0) inflight[1].fetch_sub(1);
      }
    }
    i = j;
  }
  if (any_evict) evict_events(nullptr);
  // phase 3: responses + audit + timing, in arrival order (the ring
  // wake rides AFTER the whole batch's responses, like the unary path)
  for (size_t k2 = 0; k2 < batch.size(); k2++) {
    Request& rq = batch[k2];
    Item& it = items[k2];
    std::string uri = rq.path;
    if (!rq.query.empty()) uri += "?" + rq.query;
    audit_line(rq.method, uri, it.code);
    if (it.pt.on) it.pt.last = now_ns();  // re-baseline: own encode slice
    queue_response(io, it.code, it.resp,
                   it.code == 429 ? "Retry-After: 1\r\n" : "");
    finish_write_timing(rq, it.pt, it.code, uri);
  }
  if (committed_any) {
    io.flush();  // the batch's answers hit the wire before the herd wakes
    store.ring_cv.notify_all();
  }
  return batch.size();
}

void App::handle_conn(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  ConnIO io;
  io.fd = fd;
  Request req;
  while (!stopping.load() && read_request(io, req)) {
    // batched write transactions (ISSUE 13): when the socket read that
    // carried this request brought MORE complete batchable writes (the
    // native pump pipelines whole frames), absorb the run into one
    // store transaction instead of paying per-request lock/notify
    // round-trips. Anything else — reads, watches, ops paths, fenced
    // writes — takes the unary path unchanged.
    // only a request whose body ALREADY arrived may batch: a slow sender
    // must take the unary path, where the admission slot spans the
    // blocking body read (the 429 saturation contract)
    if (batchable_write(req) &&
        io.in.size() - io.off >= req.content_len) {
      if (!read_body(io, req)) break;
      std::vector<Request> batch;
      batch.push_back(std::move(req));
      Request leftover;
      bool have_leftover = false;
      while (batch.size() < 256) {
        Request nxt;
        if (!peek_buffered_request(io, nxt)) break;
        if (batchable_write(nxt)) {
          batch.push_back(std::move(nxt));
        } else {
          leftover = std::move(nxt);
          have_leftover = true;
          break;
        }
      }
      if (batch.size() == 1 && !have_leftover) {
        // nothing arrived with it: the unary path keeps its exact
        // admission/fencing slot semantics for singletons
        if (!handle_request(io, batch[0])) break;
        continue;
      }
      exec_write_batch(io, batch);
      if (have_leftover && !handle_request(io, leftover)) break;
      continue;
    }
    if (!handle_request(io, req)) break;
  }
  io.flush();  // peer may close after its last response arrives
  close(fd);
}

static void on_term(int) {
  // async-signal-safe only: flag + wake the accept loop (shutdown() on the
  // listening socket makes accept() fail); persistence runs on the main
  // thread where taking the store mutex is legal
  if (g_app) {
    g_app->stopping.store(true);
    if (g_app->listen_fd >= 0) shutdown(g_app->listen_fd, SHUT_RDWR);
  }
}

int main(int argc, char** argv) {
  int port = 0;
  std::string address = "127.0.0.1";
  std::string audit_log, data_file, token_file;
  bool authorization = false;
  // admission limits: flags override the env knobs (mirrors mockserver.py
  // main(); 0/unset = band off)
  const char* env_ro = getenv("KWOK_TPU_MAX_INFLIGHT");
  const char* env_mu = getenv("KWOK_TPU_MAX_MUTATING_INFLIGHT");
  long max_ro = env_ro && *env_ro ? atol(env_ro) : 0;
  long max_mu = env_mu && *env_mu ? atol(env_mu) : 0;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto val = [&](const char* flag) -> const char* {
      size_t n = strlen(flag);
      if (a.rfind(flag, 0) == 0 && a.size() > n && a[n] == '=')
        return a.c_str() + n + 1;
      if (a == flag && i + 1 < argc) return argv[++i];
      return nullptr;
    };
    if (const char* v = val("--port")) port = atoi(v);
    else if (const char* v = val("--address")) address = v;
    else if (const char* v = val("--audit-log")) audit_log = v;
    else if (const char* v = val("--data-file")) data_file = v;
    else if (const char* v = val("--token-auth-file")) token_file = v;
    else if (const char* v = val("--max-inflight")) max_ro = atol(v);
    else if (const char* v = val("--max-mutating-inflight")) max_mu = atol(v);
    else if (a == "--authorization") authorization = true;
  }

  signal(SIGPIPE, SIG_IGN);

  // Heap-allocated and deliberately LEAKED: detached watch threads wait
  // on the store's shared ring condition variable, and destroying a cv
  // with live waiters (a stack App dying as main returns) is UB that
  // blocks glibc's pthread_cond_destroy — the process would hang on
  // SIGTERM exactly when watchers are attached. exit() reaps the
  // threads; the one App simply never destructs.
  App& app = *new App();
  g_app = &app;
  app.data_file = data_file;
  app.max_inflight_band[0] = max_ro;
  app.max_inflight_band[1] = max_mu;
  if (!audit_log.empty()) {
    app.audit = fopen(audit_log.c_str(), "a");
    if (!app.audit) {
      fprintf(stderr, "cannot open audit log %s\n", audit_log.c_str());
      return 1;
    }
  }
  if (!data_file.empty()) {
    FILE* f = fopen(data_file.c_str(), "r");
    if (f) {
      std::string text;
      char tmp[65536];
      size_t n;
      while ((n = fread(tmp, 1, sizeof tmp, f)) > 0) text.append(tmp, n);
      fclose(f);
      JParser p(text);
      JVal data = p.parse();
      if (p.ok) {
        app.restore_load(data);
        printf("restored store from %s\n", data_file.c_str());
        fflush(stdout);
      }
    }
  }
  if (!token_file.empty()) {
    // kube-apiserver --token-auth-file CSV: token,user,uid[,groups]
    FILE* f = fopen(token_file.c_str(), "r");
    if (!f) {
      fprintf(stderr, "cannot open token file %s\n", token_file.c_str());
      return 1;
    }
    // getline, not a fixed fgets buffer: a row longer than the buffer
    // would be split into chunks and each chunk's prefix registered as a
    // bogus accepted token — an authn loosening, not just a parse bug
    char* lineptr = nullptr;
    size_t linecap = 0;
    while (getline(&lineptr, &linecap, f) != -1) {
      std::string row = lineptr;
      row.erase(row.find_last_not_of(" \t\r\n") + 1);
      size_t comma = row.find(',');
      std::string tok =
          comma == std::string::npos ? row : row.substr(0, comma);
      if (!tok.empty()) app.auth_tokens.insert(tok);
    }
    free(lineptr);
    fclose(f);
    if (app.auth_tokens.empty()) {
      // an unusable token file must fail hard, not degrade to anonymous
      fprintf(stderr, "token file %s has no token\n", token_file.c_str());
      return 1;
    }
  }
  if (authorization) app.seed_rbac();

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) {
    perror("socket");
    return 1;
  }
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, address.c_str(), &addr.sin_addr) != 1) {
    fprintf(stderr, "bad address %s\n", address.c_str());
    return 1;
  }
  if (bind(lfd, (struct sockaddr*)&addr, sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 512) != 0) {
    perror("listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, (struct sockaddr*)&addr, &alen);
  app.listen_fd = lfd;
  const char* shown =
      (address == "0.0.0.0" || address.empty()) ? "127.0.0.1" : address.c_str();
  printf("mock apiserver listening on http://%s:%d\n", shown,
         ntohs(addr.sin_port));
  fflush(stdout);

  signal(SIGTERM, on_term);
  signal(SIGINT, on_term);

  // BOOKMARK cadence for opted-in watches (mirrors mockserver.py
  // BOOKMARK_INTERVAL; same env override; <= 0 disables). Sleeps in
  // short slices so shutdown stays prompt. Joinable — a detached thread
  // could dereference `app` (a stack local) after main returns.
  std::thread bookmark_thread;
  {
    const char* v = getenv("KWOK_TPU_BOOKMARK_INTERVAL");
    double interval = v && *v ? atof(v) : 60.0;
    if (interval > 0) {
      bookmark_thread = std::thread([&app, interval] {
        double slept = 0;
        while (!app.stopping.load()) {
          std::this_thread::sleep_for(std::chrono::milliseconds(100));
          slept += 0.1;
          if (slept + 1e-9 >= interval) {
            slept = 0;
            app.store.emit_bookmarks();
          }
        }
      });
    }
  }

  while (!app.stopping.load()) {
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR && !app.stopping.load()) continue;
      break;
    }
    std::thread(&App::handle_conn, &app, cfd).detach();
  }
  if (bookmark_thread.joinable()) bookmark_thread.join();
  // shutting down terminates watch streams: wake every ring waiter so
  // attached clients see EOF promptly instead of at process teardown
  {
    std::lock_guard<std::mutex> lk(app.store.ring_mu);
    for (auto& w : app.store.watches)
      app.store.close_watch_locked(w, /*slow=*/false);
  }
  app.store.ring_cv.notify_all();
  app.persist();
  return 0;
}
