// kwok_tpu native codec: batched JSON egress rendering.
//
// The host-side hot path of the engine is turning dirty rows into
// Kubernetes status-patch JSON (the replacement for the reference's
// per-object template rendering, pkg/kwok/controllers/renderer.go:49-89).
// Python dict building + json.dumps dominates at O(100k) rows; this
// library assembles the same bytes in one pass over flat blobs.
//
// Deliberately k8s-agnostic: all strings (condition metadata, phase names,
// timestamps, ips, container specs) arrive as caller-provided blobs with
// offset arrays, so the JSON *shape* lives here and the vocabulary stays in
// Python (kwok_tpu/edge/render.py is the semantic source of truth; parity
// is enforced by tests/test_native.py).
//
// Memory contract: every function returns the total bytes required. If that
// exceeds out_cap nothing useful is in `out`; the caller re-allocates and
// calls again. Per-row boundaries are written to out_off[0..n] so callers
// can slice row i as out[out_off[i]:out_off[i+1]].
//
// Build: g++ -O2 -shared -fPIC -o libkwokcodec.so codec.cc  (see __init__.py)

#include <cstdint>
#include <cstring>

namespace {

struct Buf {
  char* out;
  int64_t cap;
  int64_t len;  // bytes written (capped) — `need` tracks true size

  inline void put(const char* s, int64_t n) {
    if (len + n <= cap) {
      std::memcpy(out + len, s, n);
    }
    len += n;
  }
  inline void put(char c) {
    if (len + 1 <= cap) {
      out[len] = c;
    }
    len += 1;
  }
  inline void lit(const char* s) { put(s, (int64_t)std::strlen(s)); }

  // JSON-escaped string content (no surrounding quotes).
  void esc(const char* s, int64_t n) {
    static const char hex[] = "0123456789abcdef";
    for (int64_t i = 0; i < n; i++) {
      unsigned char c = (unsigned char)s[i];
      switch (c) {
        case '"': lit("\\\""); break;
        case '\\': lit("\\\\"); break;
        case '\n': lit("\\n"); break;
        case '\r': lit("\\r"); break;
        case '\t': lit("\\t"); break;
        default:
          if (c < 0x20) {
            char u[7] = {'\\', 'u', '0', '0', hex[c >> 4], hex[c & 15], 0};
            put(u, 6);
          } else {
            put((char)c);
          }
      }
    }
  }
  inline void qesc(const char* s, int64_t n) {
    put('"');
    esc(s, n);
    put('"');
  }
};

struct Slices {
  const char* blob;
  const int64_t* off;
  inline const char* ptr(int64_t i) const { return blob + off[i]; }
  inline int64_t len(int64_t i) const { return off[i + 1] - off[i]; }
};

inline void put_kv(Buf& b, const char* key, const char* v, int64_t vn) {
  b.put('"');
  b.lit(key);
  b.lit("\":");
  b.qesc(v, vn);
}

}  // namespace

extern "C" {

// {"conditions":[{lastHeartbeatTime,lastTransitionTime,message,reason,
//                 status,type} x n_conds]}
// cond_meta holds 3*n_conds strings laid out (type, reason, message) per
// condition; status of condition j for row i = bit j of cond_bits[i].
int64_t kwok_render_heartbeats(
    int32_t n_rows, const uint32_t* cond_bits, int32_t n_conds,
    const char* cond_meta_blob, const int64_t* cond_meta_off,
    const char* now, int32_t now_len,
    const char* start_blob, const int64_t* start_off,
    char* out, int64_t out_cap, int64_t* out_off) {
  Buf b{out, out_cap, 0};
  Slices meta{cond_meta_blob, cond_meta_off};
  Slices start{start_blob, start_off};
  for (int32_t i = 0; i < n_rows; i++) {
    out_off[i] = b.len;
    b.lit("{\"status\":{\"conditions\":[");
    uint32_t bits = cond_bits[i];
    for (int32_t j = 0; j < n_conds; j++) {
      if (j) b.put(',');
      b.lit("{\"lastHeartbeatTime\":");
      b.qesc(now, now_len);
      b.lit(",\"lastTransitionTime\":");
      b.qesc(start.ptr(i), start.len(i));
      b.put(',');
      put_kv(b, "message", meta.ptr(3 * j + 2), meta.len(3 * j + 2));
      b.put(',');
      put_kv(b, "reason", meta.ptr(3 * j + 1), meta.len(3 * j + 1));
      b.lit(",\"status\":");
      b.lit((bits >> j) & 1 ? "\"True\"" : "\"False\"");
      b.lit(",\"type\":");
      b.qesc(meta.ptr(3 * j), meta.len(3 * j));
      b.put('}');
    }
    b.lit("]}}");
  }
  out_off[n_rows] = b.len;
  return b.len;
}

// Full pod status patch per row:
// {"status":{"conditions":[3],"containerStatuses":[...],
//   "initContainerStatuses":[...],"hostIP","podIP","phase","startTime"}}
// phase_kind: 0 = running-like, 1 = terminated-ok, 2 = terminated-error.
// Container specs per row: fields separated by \x1f, containers by \x1e
// ("name\x1fimage\x1ename\x1fimage").
int64_t kwok_render_pod_statuses(
    int32_t n_rows, const uint8_t* phase_kind, const uint32_t* cond_bits,
    const char* phase_blob, const int64_t* phase_off,
    int32_t n_conds,
    const char* cond_names_blob, const int64_t* cond_names_off,
    const char* host_blob, const int64_t* host_off,
    const char* pod_blob, const int64_t* pod_off,
    const char* start_blob, const int64_t* start_off,
    const char* ctr_blob, const int64_t* ctr_off,
    const char* ictr_blob, const int64_t* ictr_off,
    char* out, int64_t out_cap, int64_t* out_off) {
  Buf b{out, out_cap, 0};
  Slices phase{phase_blob, phase_off};
  Slices cname{cond_names_blob, cond_names_off};
  Slices host{host_blob, host_off};
  Slices pod{pod_blob, pod_off};
  Slices start{start_blob, start_off};
  Slices ctr{ctr_blob, ctr_off};
  Slices ictr{ictr_blob, ictr_off};

  for (int32_t i = 0; i < n_rows; i++) {
    out_off[i] = b.len;
    const char* st = start.ptr(i);
    int64_t stn = start.len(i);
    uint8_t kind = phase_kind[i];
    bool ready = kind == 0;

    b.lit("{\"status\":{\"conditions\":[");
    uint32_t bits = cond_bits[i];
    for (int32_t j = 0; j < n_conds; j++) {
      if (j) b.put(',');
      b.lit("{\"lastTransitionTime\":");
      b.qesc(st, stn);
      b.lit(",\"status\":");
      b.lit((bits >> j) & 1 ? "\"True\"" : "\"False\"");
      b.lit(",\"type\":");
      b.qesc(cname.ptr(j), cname.len(j));
      b.put('}');
    }
    b.lit("],\"containerStatuses\":[");

    // containers
    const char* cs = ctr.ptr(i);
    int64_t cn = ctr.len(i);
    int64_t pos = 0;
    bool first = true;
    while (pos < cn) {
      const char* rec = cs + pos;
      const char* rec_end = (const char*)std::memchr(rec, '\x1e', cn - pos);
      int64_t rec_len = rec_end ? rec_end - rec : cn - pos;
      const char* sep = (const char*)std::memchr(rec, '\x1f', rec_len);
      int64_t name_len = sep ? sep - rec : rec_len;
      const char* img = sep ? sep + 1 : rec + rec_len;
      int64_t img_len = sep ? rec + rec_len - img : 0;
      if (!first) b.put(',');
      first = false;
      b.lit("{\"image\":");
      b.qesc(img, img_len);
      b.lit(",\"name\":");
      b.qesc(rec, name_len);
      b.lit(",\"ready\":");
      b.lit(ready ? "true" : "false");
      b.lit(",\"restartCount\":0,\"state\":");
      if (kind == 0) {
        b.lit("{\"running\":{\"startedAt\":");
        b.qesc(st, stn);
        b.lit("}}");
      } else {
        b.lit("{\"terminated\":{\"exitCode\":");
        b.lit(kind == 1 ? "0" : "1");
        b.lit(",\"finishedAt\":");
        b.qesc(st, stn);
        b.lit(",\"reason\":");
        b.lit(kind == 1 ? "\"Completed\"" : "\"Error\"");
        b.lit(",\"startedAt\":");
        b.qesc(st, stn);
        b.lit("}}");
      }
      b.put('}');
      pos += rec_len + (rec_end ? 1 : 0);
    }

    b.lit("],\"initContainerStatuses\":[");
    const char* is = ictr.ptr(i);
    int64_t in_ = ictr.len(i);
    pos = 0;
    first = true;
    while (pos < in_) {
      const char* rec = is + pos;
      const char* rec_end = (const char*)std::memchr(rec, '\x1e', in_ - pos);
      int64_t rec_len = rec_end ? rec_end - rec : in_ - pos;
      const char* sep = (const char*)std::memchr(rec, '\x1f', rec_len);
      int64_t name_len = sep ? sep - rec : rec_len;
      const char* img = sep ? sep + 1 : rec + rec_len;
      int64_t img_len = sep ? rec + rec_len - img : 0;
      if (!first) b.put(',');
      first = false;
      b.lit("{\"image\":");
      b.qesc(img, img_len);
      b.lit(",\"name\":");
      b.qesc(rec, name_len);
      b.lit(
          ",\"ready\":true,\"restartCount\":0,\"state\":{\"terminated\":"
          "{\"exitCode\":0,\"finishedAt\":");
      b.qesc(st, stn);
      b.lit(",\"reason\":\"Completed\",\"startedAt\":");
      b.qesc(st, stn);
      b.lit("}}}");
      pos += rec_len + (rec_end ? 1 : 0);
    }

    b.lit("],\"hostIP\":");
    b.qesc(host.ptr(i), host.len(i));
    b.lit(",\"podIP\":");
    b.qesc(pod.ptr(i), pod.len(i));
    b.lit(",\"phase\":");
    b.qesc(phase.ptr(i), phase.len(i));
    b.lit(",\"startTime\":");
    b.qesc(st, stn);
    b.lit("}}");
  }
  out_off[n_rows] = b.len;
  return b.len;
}

// Keep in lockstep with ABI_VERSION in native/__init__.py — a mismatch
// triggers delete+rebuild loops (and bricks hosts without a compiler).
// ABI 8: pump.cc grew kwok_pump_stats (send-path attribution).
int32_t kwok_codec_abi_version() { return 8; }

}  // extern "C"
