// kwok_tpu native codec: batched JSON egress rendering.
//
// The host-side hot path of the engine is turning dirty rows into
// Kubernetes status-patch JSON (the replacement for the reference's
// per-object template rendering, pkg/kwok/controllers/renderer.go:49-89).
// Python dict building + json.dumps dominates at O(100k) rows; this
// library assembles the same bytes in one pass over flat blobs.
//
// Deliberately k8s-agnostic: all strings (condition metadata, phase names,
// timestamps, ips, container specs) arrive as caller-provided blobs with
// offset arrays, so the JSON *shape* lives here and the vocabulary stays in
// Python (kwok_tpu/edge/render.py is the semantic source of truth; parity
// is enforced by tests/test_native.py).
//
// Memory contract: every function returns the total bytes required. If that
// exceeds out_cap nothing useful is in `out`; the caller re-allocates and
// calls again. Per-row boundaries are written to out_off[0..n] so callers
// can slice row i as out[out_off[i]:out_off[i+1]].
//
// Build: g++ -O2 -shared -fPIC -o libkwokcodec.so codec.cc  (see __init__.py)

#include <cstdint>
#include <cstring>

namespace {

struct Buf {
  char* out;
  int64_t cap;
  int64_t len;  // bytes written (capped) — `need` tracks true size

  inline void put(const char* s, int64_t n) {
    if (len + n <= cap) {
      std::memcpy(out + len, s, n);
    }
    len += n;
  }
  inline void put(char c) {
    if (len + 1 <= cap) {
      out[len] = c;
    }
    len += 1;
  }
  inline void lit(const char* s) { put(s, (int64_t)std::strlen(s)); }

  // JSON-escaped string content (no surrounding quotes).
  void esc(const char* s, int64_t n) {
    static const char hex[] = "0123456789abcdef";
    for (int64_t i = 0; i < n; i++) {
      unsigned char c = (unsigned char)s[i];
      switch (c) {
        case '"': lit("\\\""); break;
        case '\\': lit("\\\\"); break;
        case '\n': lit("\\n"); break;
        case '\r': lit("\\r"); break;
        case '\t': lit("\\t"); break;
        default:
          if (c < 0x20) {
            char u[7] = {'\\', 'u', '0', '0', hex[c >> 4], hex[c & 15], 0};
            put(u, 6);
          } else {
            put((char)c);
          }
      }
    }
  }
  inline void qesc(const char* s, int64_t n) {
    put('"');
    esc(s, n);
    put('"');
  }
};

struct Slices {
  const char* blob;
  const int64_t* off;
  inline const char* ptr(int64_t i) const { return blob + off[i]; }
  inline int64_t len(int64_t i) const { return off[i + 1] - off[i]; }
};

inline void put_kv(Buf& b, const char* key, const char* v, int64_t vn) {
  b.put('"');
  b.lit(key);
  b.lit("\":");
  b.qesc(v, vn);
}

// containerStatuses / initContainerStatuses array CONTENT (no brackets)
// from packed records "name\x1fimage\x1e...". init=true renders the
// terminated-Completed init-container shape regardless of kind. ONE copy
// shared by the legacy batch renderer and the template splicer, so the
// two paths cannot drift byte-wise. `ready` is passed separately from
// `kind`: render.py marks containers ready ONLY in phase Running, while
// the container STATE tracks terminated-vs-running — the legacy caller
// collapses the two (its historical shape), the template caller bakes
// ready per phase at compile time, matching render.py exactly.
void put_containers(Buf& b, const char* cs, int64_t cn, uint8_t kind,
                    bool ready, const char* st, int64_t stn, bool init) {
  int64_t pos = 0;
  bool first = true;
  while (pos < cn) {
    const char* rec = cs + pos;
    const char* rec_end = (const char*)std::memchr(rec, '\x1e', cn - pos);
    int64_t rec_len = rec_end ? rec_end - rec : cn - pos;
    const char* sep = (const char*)std::memchr(rec, '\x1f', rec_len);
    int64_t name_len = sep ? sep - rec : rec_len;
    const char* img = sep ? sep + 1 : rec + rec_len;
    int64_t img_len = sep ? rec + rec_len - img : 0;
    if (!first) b.put(',');
    first = false;
    b.lit("{\"image\":");
    b.qesc(img, img_len);
    b.lit(",\"name\":");
    b.qesc(rec, name_len);
    if (init) {
      b.lit(
          ",\"ready\":true,\"restartCount\":0,\"state\":{\"terminated\":"
          "{\"exitCode\":0,\"finishedAt\":");
      b.qesc(st, stn);
      b.lit(",\"reason\":\"Completed\",\"startedAt\":");
      b.qesc(st, stn);
      b.lit("}}}");
    } else {
      b.lit(",\"ready\":");
      b.lit(ready ? "true" : "false");
      b.lit(",\"restartCount\":0,\"state\":");
      if (kind == 0) {
        b.lit("{\"running\":{\"startedAt\":");
        b.qesc(st, stn);
        b.lit("}}");
      } else {
        b.lit("{\"terminated\":{\"exitCode\":");
        b.lit(kind == 1 ? "0" : "1");
        b.lit(",\"finishedAt\":");
        b.qesc(st, stn);
        b.lit(",\"reason\":");
        b.lit(kind == 1 ? "\"Completed\"" : "\"Error\"");
        b.lit(",\"startedAt\":");
        b.qesc(st, stn);
        b.lit("}}");
      }
      b.put('}');
    }
    pos += rec_len + (rec_end ? 1 : 0);
  }
}

}  // namespace

// cross-TU internals of libkwokcodec.so (same shared object):
// the canonical status fingerprint (ingest.cc) and the prefixed batch
// send (pump.cc) the fused emit call composes with.
extern "C" void kwok_fingerprint_statuses(const char* blob,
                                          const int64_t* off, int32_t n,
                                          uint64_t* out);
extern "C" int64_t kwok_pump_send2(
    int64_t handle, int32_t n, const char* method, const char* base,
    int64_t base_len, const char* path_blob, const int64_t* path_off,
    const char* suffix, int64_t suffix_len, const char* ctype,
    int64_t ctype_len, const char* body_blob, const int64_t* body_off,
    int32_t* status_out);

extern "C" {

// {"conditions":[{lastHeartbeatTime,lastTransitionTime,message,reason,
//                 status,type} x n_conds]}
// cond_meta holds 3*n_conds strings laid out (type, reason, message) per
// condition; status of condition j for row i = bit j of cond_bits[i].
int64_t kwok_render_heartbeats(
    int32_t n_rows, const uint32_t* cond_bits, int32_t n_conds,
    const char* cond_meta_blob, const int64_t* cond_meta_off,
    const char* now, int32_t now_len,
    const char* start_blob, const int64_t* start_off,
    char* out, int64_t out_cap, int64_t* out_off) {
  Buf b{out, out_cap, 0};
  Slices meta{cond_meta_blob, cond_meta_off};
  Slices start{start_blob, start_off};
  for (int32_t i = 0; i < n_rows; i++) {
    out_off[i] = b.len;
    b.lit("{\"status\":{\"conditions\":[");
    uint32_t bits = cond_bits[i];
    for (int32_t j = 0; j < n_conds; j++) {
      if (j) b.put(',');
      b.lit("{\"lastHeartbeatTime\":");
      b.qesc(now, now_len);
      b.lit(",\"lastTransitionTime\":");
      b.qesc(start.ptr(i), start.len(i));
      b.put(',');
      put_kv(b, "message", meta.ptr(3 * j + 2), meta.len(3 * j + 2));
      b.put(',');
      put_kv(b, "reason", meta.ptr(3 * j + 1), meta.len(3 * j + 1));
      b.lit(",\"status\":");
      b.lit((bits >> j) & 1 ? "\"True\"" : "\"False\"");
      b.lit(",\"type\":");
      b.qesc(meta.ptr(3 * j), meta.len(3 * j));
      b.put('}');
    }
    b.lit("]}}");
  }
  out_off[n_rows] = b.len;
  return b.len;
}

// Full pod status patch per row:
// {"status":{"conditions":[3],"containerStatuses":[...],
//   "initContainerStatuses":[...],"hostIP","podIP","phase","startTime"}}
// phase_kind: 0 = running-like, 1 = terminated-ok, 2 = terminated-error.
// Container specs per row: fields separated by \x1f, containers by \x1e
// ("name\x1fimage\x1ename\x1fimage").
int64_t kwok_render_pod_statuses(
    int32_t n_rows, const uint8_t* phase_kind, const uint32_t* cond_bits,
    const char* phase_blob, const int64_t* phase_off,
    int32_t n_conds,
    const char* cond_names_blob, const int64_t* cond_names_off,
    const char* host_blob, const int64_t* host_off,
    const char* pod_blob, const int64_t* pod_off,
    const char* start_blob, const int64_t* start_off,
    const char* ctr_blob, const int64_t* ctr_off,
    const char* ictr_blob, const int64_t* ictr_off,
    char* out, int64_t out_cap, int64_t* out_off) {
  Buf b{out, out_cap, 0};
  Slices phase{phase_blob, phase_off};
  Slices cname{cond_names_blob, cond_names_off};
  Slices host{host_blob, host_off};
  Slices pod{pod_blob, pod_off};
  Slices start{start_blob, start_off};
  Slices ctr{ctr_blob, ctr_off};
  Slices ictr{ictr_blob, ictr_off};

  for (int32_t i = 0; i < n_rows; i++) {
    out_off[i] = b.len;
    const char* st = start.ptr(i);
    int64_t stn = start.len(i);
    uint8_t kind = phase_kind[i];

    b.lit("{\"status\":{\"conditions\":[");
    uint32_t bits = cond_bits[i];
    for (int32_t j = 0; j < n_conds; j++) {
      if (j) b.put(',');
      b.lit("{\"lastTransitionTime\":");
      b.qesc(st, stn);
      b.lit(",\"status\":");
      b.lit((bits >> j) & 1 ? "\"True\"" : "\"False\"");
      b.lit(",\"type\":");
      b.qesc(cname.ptr(j), cname.len(j));
      b.put('}');
    }
    b.lit("],\"containerStatuses\":[");
    put_containers(b, ctr.ptr(i), ctr.len(i), kind, kind == 0, st, stn,
                   false);
    b.lit("],\"initContainerStatuses\":[");
    put_containers(b, ictr.ptr(i), ictr.len(i), kind, kind == 0, st, stn,
                   true);
    b.lit("],\"hostIP\":");
    b.qesc(host.ptr(i), host.len(i));
    b.lit(",\"podIP\":");
    b.qesc(pod.ptr(i), pod.len(i));
    b.lit(",\"phase\":");
    b.qesc(phase.ptr(i), phase.len(i));
    b.lit(",\"startTime\":");
    b.qesc(st, stn);
    b.lit("}}");
  }
  out_off[n_rows] = b.len;
  return b.len;
}

// AOT-template emit (ISSUE 14): splice per-row values into the compiled
// patch-body templates (models/compiler.py EmitTemplates wire format) and
// — when `pump` names an open pump — ship the whole batch in the SAME
// call, so a dirty-row batch goes template -> body slab -> wire without
// re-entering Python.
//
// Segment codes (keep in lockstep with compiler.py EMIT_*):
//   0 literal [seg_a=lit offset, seg_b=len]   1 start time ("" -> now)
//   2 hostIP   3 podIP   4 containers   5 init containers
//   6 condition status '"True"'/'"False"' from cond bit seg_a
//
// Memory contract: same as the renderers above — returns total body
// bytes required; if that exceeds out_cap NOTHING was fingerprinted or
// sent (the caller re-allocates and calls again), so the send happens
// exactly once. On success fp_out[i] (when non-null) carries each body's
// canonical status fingerprint (ingest.cc's algorithm — the echo-drop
// seed), and with a pump the batch is sent as
// "PATCH {base}{path[i]}{suffix}" with content type `ctype`, statuses in
// status_out (pump.cc failure contract: 0 = connection death).
int64_t kwok_emit_pods(
    int64_t pump, int32_t n_rows,
    const int32_t* tpl_id, const uint32_t* cond_bits,
    const char* lit_blob, const int32_t* seg_code, const int64_t* seg_a,
    const int64_t* seg_b, const int64_t* tpl_off, const uint8_t* tpl_kind,
    const uint8_t* tpl_ready,
    const char* host_blob, const int64_t* host_off,
    const char* pod_blob, const int64_t* pod_off,
    const char* start_blob, const int64_t* start_off,
    const char* ctr_blob, const int64_t* ctr_off,
    const char* ictr_blob, const int64_t* ictr_off,
    const char* now, int32_t now_len,
    char* out, int64_t out_cap, int64_t* out_off,
    uint64_t* fp_out,
    const char* base, int64_t base_len,
    const char* path_blob, const int64_t* path_off,
    const char* suffix, int64_t suffix_len,
    const char* ctype, int64_t ctype_len,
    int32_t* status_out) {
  Buf b{out, out_cap, 0};
  Slices host{host_blob, host_off};
  Slices pod{pod_blob, pod_off};
  Slices start{start_blob, start_off};
  Slices ctr{ctr_blob, ctr_off};
  Slices ictr{ictr_blob, ictr_off};
  for (int32_t i = 0; i < n_rows; i++) {
    out_off[i] = b.len;
    int32_t t = tpl_id[i];
    const char* st = start.ptr(i);
    int64_t stn = start.len(i);
    if (stn == 0) {  // absent creationTimestamp: the batch-hoisted now
      st = now;
      stn = now_len;
    }
    uint8_t kind = tpl_kind[t];
    bool ready = tpl_ready[t] != 0;
    uint32_t bits = cond_bits[i];
    for (int64_t s = tpl_off[t]; s < tpl_off[t + 1]; s++) {
      switch (seg_code[s]) {
        case 0: b.put(lit_blob + seg_a[s], seg_b[s]); break;
        case 1: b.esc(st, stn); break;
        case 2: b.esc(host.ptr(i), host.len(i)); break;
        case 3: b.esc(pod.ptr(i), pod.len(i)); break;
        case 4: put_containers(b, ctr.ptr(i), ctr.len(i), kind, ready, st,
                               stn, false); break;
        case 5: put_containers(b, ictr.ptr(i), ictr.len(i), kind, ready,
                               st, stn, true); break;
        case 6: b.lit((bits >> seg_a[s]) & 1 ? "\"True\"" : "\"False\"");
                break;
      }
    }
  }
  out_off[n_rows] = b.len;
  if (b.len > out_cap) return b.len;  // nothing fingerprinted, nothing sent
  if (fp_out) kwok_fingerprint_statuses(out, out_off, n_rows, fp_out);
  if (pump && status_out) {
    kwok_pump_send2(pump, n_rows, "PATCH", base, base_len, path_blob,
                    path_off, suffix, suffix_len, ctype, ctype_len, out,
                    out_off, status_out);
  }
  return b.len;
}

// Keep in lockstep with ABI_VERSION in native/__init__.py — a mismatch
// triggers delete+rebuild loops (and bricks hosts without a compiler).
// ABI 9: kwok_emit_pods (AOT-template splice + fused pump send) and
// pump.cc kwok_pump_send2.
int32_t kwok_codec_abi_version() { return 9; }

}  // extern "C"
