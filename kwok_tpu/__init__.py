"""kwok-tpu: a TPU-native cluster lifecycle simulation framework.

Capability target: the KWOK toolkit (reference: /root/reference, a Go codebase)
— simulate thousands-to-millions of fake Kubernetes nodes and pods against a
real control plane — re-designed TPU-first:

- Cluster state is a sharded struct-of-arrays tensor (`kwok_tpu.ops.state`).
- Lifecycle rules (selector -> delay -> next status; the generalization of the
  reference's status templates, pkg/kwok/controllers/templates/) compile to
  dense rule tables (`kwok_tpu.models`) evaluated by a single jitted tick
  kernel (`kwok_tpu.ops.tick`), vmapped over object rows and `shard_map`ped
  over a `jax.sharding.Mesh` (`kwok_tpu.parallel`).
- Only non-empty status-patch diffs cross back to the apiserver over the
  list/watch/patch edge (`kwok_tpu.edge`).
- `kwok_tpu.kwokctl` is the orchestration plane: it stands up a full local
  control plane (etcd, kube-apiserver, kube-controller-manager,
  kube-scheduler, the simulator, Prometheus), mirroring the reference's
  pkg/kwokctl layer map (SURVEY.md section 1).
"""

__version__ = "0.1.0"
