"""Lifecycle rule models.

The reference drives lifecycle simulation with three embedded Go templates
(pkg/kwok/controllers/templates/templates.go:24-33) selected by hard-coded
controller logic. Here the same behavior is expressed as data: a list of
`LifecycleRule`s (selector + delay + next-state), the generalization that
Stage CRDs later became (SURVEY.md, "Snapshot vintage"). Rules compile to
dense arrays (`compile_rules`) executed by the tick kernel in kwok_tpu.ops.
"""

from kwok_tpu.models.lifecycle import (
    Delay,
    LifecycleRule,
    PhaseSpace,
    ResourceKind,
    StatusEffect,
)
from kwok_tpu.models.compiler import (
    CompiledRules,
    EmitTemplates,
    compile_emit_templates,
    compile_rules,
)
from kwok_tpu.models.defaults import (
    default_node_rules,
    default_pod_rules,
    default_rules,
)

__all__ = [
    "Delay",
    "LifecycleRule",
    "PhaseSpace",
    "ResourceKind",
    "StatusEffect",
    "CompiledRules",
    "EmitTemplates",
    "compile_emit_templates",
    "compile_rules",
    "default_node_rules",
    "default_pod_rules",
    "default_rules",
]
