"""Ahead-of-time rule compiler: LifecycleRule list -> dense device tables.

Replaces the reference's runtime template rendering
(pkg/kwok/controllers/renderer.go:30-89, parse-and-cache per template): here
ALL decision logic is compiled once, before the engine starts, into flat
arrays the tick kernel broadcasts against. Rendering of the full status
document happens only at the API boundary for dirty rows.

The compiled form is deliberately framework-agnostic numpy; kwok_tpu.ops.tick
moves it to device once and closes over it in the jitted tick.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from kwok_tpu.models.lifecycle import (
    DELETION_ANY,
    LifecycleRule,
    PhaseSpace,
    PHASE_SPACES,
    ResourceKind,
)

NO_RULE = np.int32(-1)


@dataclasses.dataclass(frozen=True)
class CompiledRules:
    """Dense rule table for ONE resource kind.

    All arrays have length R (number of rules); rule order encodes priority
    (first match wins, like the reference's fixed controller ordering).
    """

    resource: ResourceKind
    space: PhaseSpace
    # uint32 bitmask over phase ids the rule matches from.
    from_mask: np.ndarray
    # int8: DELETION_ANY(-1) / DELETION_ABSENT(0) / DELETION_PRESENT(1).
    deletion: np.ndarray
    # int32 selector bit index into the row's sel_bits, or -1 for "all".
    selector_bit: np.ndarray
    # Delay distribution per rule.
    delay_kind: np.ndarray  # int8 DelayKind
    delay_a: np.ndarray  # float32
    delay_b: np.ndarray  # float32
    # Effect.
    to_phase: np.ndarray  # int32 phase id
    cond_assign: np.ndarray  # uint32: which condition bits the rule writes
    cond_value: np.ndarray  # uint32: the values written for assigned bits
    is_delete: np.ndarray  # bool
    # float32 Stage spec.weight; 0 = deterministic first-match rule, > 0 =
    # member of the stochastic pool (see LifecycleRule.weight).
    weight: np.ndarray
    # Host-side metadata (not shipped to device).
    names: tuple[str, ...]
    selector_names: tuple[str, ...]  # bit index -> selector name

    @property
    def num_rules(self) -> int:
        return int(self.from_mask.shape[0])


def compile_rules(
    rules: list[LifecycleRule],
    resource: ResourceKind,
    space: PhaseSpace | None = None,
) -> CompiledRules:
    space = space or PHASE_SPACES[resource]
    mine = [r for r in rules if r.resource == resource]

    # Upstream Stage documents may name phases outside the canonical
    # vocabulary (any string is a legal .status.phase). Extend the space by
    # APPENDING the unknown names: the canonical prefix keeps its ids, so
    # ingest/render constants (Pending, Gone, ...) stay valid, and two rule
    # sets differ only where their rules do (federation grouping keys
    # include the phase names).
    extra: list[str] = []
    for r in mine:
        for p in (*r.from_phases, r.effect.to_phase):
            if p and p not in space.phases and p not in extra:
                extra.append(p)
    if extra:
        space = PhaseSpace(
            phases=space.phases + tuple(extra), conditions=space.conditions
        )

    selector_names: list[str] = []

    def selector_id(name: str | None) -> int:
        if name is None:
            return -1
        if name not in selector_names:
            if len(selector_names) >= 32:
                raise ValueError("at most 32 distinct selectors per resource")
            selector_names.append(name)
        return selector_names.index(name)

    n = len(mine)
    from_mask = np.zeros(n, np.uint32)
    deletion = np.zeros(n, np.int8)
    selector_bit = np.zeros(n, np.int32)
    delay_kind = np.zeros(n, np.int8)
    delay_a = np.zeros(n, np.float32)
    delay_b = np.zeros(n, np.float32)
    to_phase = np.zeros(n, np.int32)
    cond_assign = np.zeros(n, np.uint32)
    cond_value = np.zeros(n, np.uint32)
    is_delete = np.zeros(n, bool)
    weight = np.zeros(n, np.float32)

    for i, r in enumerate(mine):
        if r.weight < 0:
            raise ValueError(f"rule {r.name!r}: weight must be >= 0")
        weight[i] = float(r.weight)
        to_id = space.phase_id(r.effect.to_phase)
        if r.from_phases:
            mask = 0
            for p in r.from_phases:
                mask |= 1 << space.phase_id(p)
        else:
            # empty from_phases = match any phase (upstream Stage semantics
            # for an absent selector.matchPhases), EXCEPT the rule's own
            # target phase for non-delete rules — otherwise the rule re-fires
            # from the phase it just wrote, patching the apiserver forever.
            mask = 0xFFFFFFFF
            if not r.effect.delete:
                mask &= ~(1 << to_id) & 0xFFFFFFFF
        from_mask[i] = mask
        deletion[i] = np.int8(r.deletion)
        selector_bit[i] = selector_id(r.selector)
        delay_kind[i] = int(r.delay.kind)
        delay_a[i] = r.delay.a
        delay_b[i] = r.delay.b
        to_phase[i] = to_id
        ca = 0
        cv = 0
        for cond, val in r.effect.conditions.items():
            bit = 1 << space.condition_bit(cond)
            ca |= bit
            if val:
                cv |= bit
        cond_assign[i] = ca
        cond_value[i] = cv
        is_delete[i] = r.effect.delete

    return CompiledRules(
        resource=resource,
        space=space,
        from_mask=from_mask,
        deletion=deletion,
        selector_bit=selector_bit,
        delay_kind=delay_kind,
        delay_a=delay_a,
        delay_b=delay_b,
        to_phase=to_phase,
        cond_assign=cond_assign,
        cond_value=cond_value,
        is_delete=is_delete,
        weight=weight,
        names=tuple(r.name for r in mine),
        selector_names=tuple(selector_names),
    )


def match_rules_host(
    table: CompiledRules,
    phase: int,
    sel_bits: int,
    has_deletion: bool,
) -> list[int]:
    """All rule indices whose guards (phase mask, deletion requirement,
    selector bit) match, in priority order. Pure-python oracle mirror of
    the device-side [C, R] match in kwok_tpu.ops.tick."""
    out = []
    for i in range(table.num_rules):
        if not (int(table.from_mask[i]) >> phase) & 1:
            continue
        d = int(table.deletion[i])
        if d != DELETION_ANY and bool(d) != has_deletion:
            continue
        sb = int(table.selector_bit[i])
        if sb >= 0 and not (sel_bits >> sb) & 1:
            continue
        out.append(i)
    return out


def choose_rule_host(table: CompiledRules, matches: list[int], u2: float) -> int:
    """Select among matched rules exactly like the tick kernel:

    - no matches -> -1;
    - first match unweighted (weight 0) -> first match (deterministic);
    - first match weighted -> weighted-random among ALL matching weighted
      rules, P(i) proportional to weight[i], via the caller's uniform u2 in
      [0, 1) (the device uses its per-row PRNG draw).
    """
    if not matches:
        return -1
    first = matches[0]
    if float(table.weight[first]) <= 0:
        return first
    pool = [i for i in matches if float(table.weight[i]) > 0]
    total = sum(float(table.weight[i]) for i in pool)
    target = u2 * total
    acc = 0.0
    for i in pool:
        acc += float(table.weight[i])
        if acc > target:
            return i
    return pool[-1]


def match_rule_host(
    table: CompiledRules,
    phase: int,
    sel_bits: int,
    has_deletion: bool,
    u2: float = 0.0,
) -> int:
    """Pure-python single-row rule matcher (the oracle for property tests).

    Mirrors the device-side selection in kwok_tpu.ops.tick exactly: first
    rule (lowest index) whose guards all match — or, when the first match
    is weighted, the weighted draw made by `choose_rule_host` with `u2`
    (the default 0.0 picks the lowest-index weighted match)."""
    return choose_rule_host(
        table, match_rules_host(table, phase, sel_bits, has_deletion), u2
    )
