"""Ahead-of-time rule compiler: LifecycleRule list -> dense device tables.

Replaces the reference's runtime template rendering
(pkg/kwok/controllers/renderer.go:30-89, parse-and-cache per template): here
ALL decision logic is compiled once, before the engine starts, into flat
arrays the tick kernel broadcasts against. Rendering of the full status
document happens only at the API boundary for dirty rows.

The compiled form is deliberately framework-agnostic numpy; kwok_tpu.ops.tick
moves it to device once and closes over it in the jitted tick.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from kwok_tpu.models.lifecycle import (
    DELETION_ANY,
    LifecycleRule,
    PhaseSpace,
    PHASE_SPACES,
    ResourceKind,
)

NO_RULE = np.int32(-1)

# --- AOT patch-body templates (ISSUE 14) ------------------------------------
#
# Segment codes for EmitTemplates: a compiled Stage rule's status-patch body
# lowered to literal byte runs plus typed holes the native codec splices
# per-row values into (codec.cc kwok_emit_pods). The JSON *shape* — key
# order, punctuation, the rule's target phase, condition types — is fixed
# here at compile time; only genuinely per-row values stay holes.
EMIT_LIT = 0     # literal bytes: seg_a = offset into lit_blob, seg_b = len
EMIT_START = 1   # row start/creation timestamp (batch "now" when empty)
EMIT_HOST = 2    # row hostIP
EMIT_POD = 3     # row podIP
EMIT_CTRS = 4    # containerStatuses records ("name\x1fimage\x1e...")
EMIT_ICTRS = 5   # initContainerStatuses records
EMIT_COND = 6    # '"True"'/'"False"' from row condition bit seg_a

# The three pod conditions the reference template asserts
# (pod.status.tpl; edge/render.py render_pod_status).
_POD_EMIT_CONDITIONS = ("Initialized", "Ready", "ContainersReady")


def _esc_json(s: str) -> bytes:
    """JSON string-content escaping, byte-identical to codec.cc Buf::esc
    (raw UTF-8 for printable text, \\u00xx for control chars) — baked
    literals must match what the runtime splicer would have written."""
    out = bytearray()
    for ch in s.encode():
        if ch == 0x22:
            out += b'\\"'
        elif ch == 0x5C:
            out += b"\\\\"
        elif ch == 0x0A:
            out += b"\\n"
        elif ch == 0x0D:
            out += b"\\r"
        elif ch == 0x09:
            out += b"\\t"
        elif ch < 0x20:
            out += b"\\u%04x" % ch
        else:
            out.append(ch)
    return bytes(out)


@dataclasses.dataclass(frozen=True)
class EmitTemplates:
    """Pod status-patch bodies as byte templates, one per target phase.

    The tick wire hands emit a row's post-transition phase id and
    condition bits; everything else in the patch body is either fixed by
    the phase (the template) or a per-row column (the holes). Every
    rule's compile-time ``to_phase`` is a phase id, so "each rule's
    patch body" dedups to one template per distinct target phase and
    ``phase_tpl`` is the whole mapping the splicer needs.

    Arrays are the wire format codec.cc consumes directly:
    ``seg_code``/``seg_a``/``seg_b`` are the concatenated segment tables
    of all templates, template t spanning ``tpl_off[t]:tpl_off[t+1]``.
    """

    lit_blob: bytes
    seg_code: np.ndarray  # int32, EMIT_* per segment
    seg_a: np.ndarray  # int64: literal offset / condition bit
    seg_b: np.ndarray  # int64: literal length
    tpl_off: np.ndarray  # int64 [T+1]
    tpl_kind: np.ndarray  # uint8: 0 running-like / 1 terminated-ok / 2 -err
    # uint8: containers render ready:true — ONLY phase Running, per
    # render.py (the legacy codec collapsed this into tpl_kind==0, which
    # silently marked Pending/Terminating/custom-phase containers ready;
    # the compiled form follows the semantic source of truth)
    tpl_ready: np.ndarray
    phase_tpl: np.ndarray  # int32: phase id -> template id (-1 = slow path)
    phase_names: tuple[str, ...]  # template id -> phase name


class _TplBuilder:
    def __init__(self) -> None:
        self.lit = bytearray()
        self.code: list[int] = []
        self.a: list[int] = []
        self.b: list[int] = []
        self.off: list[int] = [0]

    def text(self, data: bytes) -> None:
        # merge adjacent literals so each template is a handful of segs
        if self.code and len(self.code) > self.off[-1] and (
            self.code[-1] == EMIT_LIT
            and self.a[-1] + self.b[-1] == len(self.lit)
        ):
            self.b[-1] += len(data)
        else:
            self.code.append(EMIT_LIT)
            self.a.append(len(self.lit))
            self.b.append(len(data))
        self.lit += data

    def hole(self, code: int, param: int = 0) -> None:
        self.code.append(code)
        self.a.append(param)
        self.b.append(0)

    def end_template(self) -> None:
        self.off.append(len(self.code))


def compile_emit_templates(table: CompiledRules) -> EmitTemplates:
    """Lower every reachable pod status-patch body to a byte template.

    One template per phase in the table's (possibly Stage-extended)
    phase space, except the terminal "Gone" (those rows never emit).
    Raises KeyError when the space lacks the canonical pod conditions —
    callers treat that as "no templates" and keep the generic renderer.
    """
    space = table.space
    cond_bits = [space.condition_bit(c) for c in _POD_EMIT_CONDITIONS]
    b = _TplBuilder()
    kinds: list[int] = []
    readys: list[int] = []
    names: list[str] = []
    phase_tpl = np.full(len(space.phases), -1, np.int32)
    for pid, phase in enumerate(space.phases):
        if phase == "Gone":
            continue
        phase_tpl[pid] = len(names)
        names.append(phase)
        kinds.append(1 if phase == "Succeeded" else 2 if phase == "Failed" else 0)
        readys.append(1 if phase == "Running" else 0)
        b.text(b'{"status":{"conditions":[')
        for j, (cname, bit) in enumerate(zip(_POD_EMIT_CONDITIONS, cond_bits)):
            if j:
                b.text(b",")
            b.text(b'{"lastTransitionTime":"')
            b.hole(EMIT_START)
            b.text(b'","status":')
            b.hole(EMIT_COND, bit)
            b.text(b',"type":"' + _esc_json(cname) + b'"}')
        b.text(b'],"containerStatuses":[')
        b.hole(EMIT_CTRS)
        b.text(b'],"initContainerStatuses":[')
        b.hole(EMIT_ICTRS)
        b.text(b'],"hostIP":"')
        b.hole(EMIT_HOST)
        b.text(b'","podIP":"')
        b.hole(EMIT_POD)
        b.text(b'","phase":"' + _esc_json(phase) + b'","startTime":"')
        b.hole(EMIT_START)
        b.text(b'"}}')
        b.end_template()
    return EmitTemplates(
        lit_blob=bytes(b.lit),
        seg_code=np.asarray(b.code, np.int32),
        seg_a=np.asarray(b.a, np.int64),
        seg_b=np.asarray(b.b, np.int64),
        tpl_off=np.asarray(b.off, np.int64),
        tpl_kind=np.asarray(kinds, np.uint8),
        tpl_ready=np.asarray(readys, np.uint8),
        phase_tpl=phase_tpl,
        phase_names=tuple(names),
    )


@dataclasses.dataclass(frozen=True)
class CompiledRules:
    """Dense rule table for ONE resource kind.

    All arrays have length R (number of rules); rule order encodes priority
    (first match wins, like the reference's fixed controller ordering).
    """

    resource: ResourceKind
    space: PhaseSpace
    # uint32 bitmask over phase ids the rule matches from.
    from_mask: np.ndarray
    # int8: DELETION_ANY(-1) / DELETION_ABSENT(0) / DELETION_PRESENT(1).
    deletion: np.ndarray
    # int32 selector bit index into the row's sel_bits, or -1 for "all".
    selector_bit: np.ndarray
    # Delay distribution per rule.
    delay_kind: np.ndarray  # int8 DelayKind
    delay_a: np.ndarray  # float32
    delay_b: np.ndarray  # float32
    # Effect.
    to_phase: np.ndarray  # int32 phase id
    cond_assign: np.ndarray  # uint32: which condition bits the rule writes
    cond_value: np.ndarray  # uint32: the values written for assigned bits
    is_delete: np.ndarray  # bool
    # float32 Stage spec.weight; 0 = deterministic first-match rule, > 0 =
    # member of the stochastic pool (see LifecycleRule.weight).
    weight: np.ndarray
    # Host-side metadata (not shipped to device).
    names: tuple[str, ...]
    selector_names: tuple[str, ...]  # bit index -> selector name

    @property
    def num_rules(self) -> int:
        return int(self.from_mask.shape[0])


def compile_rules(
    rules: list[LifecycleRule],
    resource: ResourceKind,
    space: PhaseSpace | None = None,
) -> CompiledRules:
    space = space or PHASE_SPACES[resource]
    mine = [r for r in rules if r.resource == resource]

    # Upstream Stage documents may name phases outside the canonical
    # vocabulary (any string is a legal .status.phase). Extend the space by
    # APPENDING the unknown names: the canonical prefix keeps its ids, so
    # ingest/render constants (Pending, Gone, ...) stay valid, and two rule
    # sets differ only where their rules do (federation grouping keys
    # include the phase names).
    extra: list[str] = []
    for r in mine:
        for p in (*r.from_phases, r.effect.to_phase):
            if p and p not in space.phases and p not in extra:
                extra.append(p)
    if extra:
        space = PhaseSpace(
            phases=space.phases + tuple(extra), conditions=space.conditions
        )

    selector_names: list[str] = []

    def selector_id(name: str | None) -> int:
        if name is None:
            return -1
        if name not in selector_names:
            if len(selector_names) >= 32:
                raise ValueError("at most 32 distinct selectors per resource")
            selector_names.append(name)
        return selector_names.index(name)

    n = len(mine)
    from_mask = np.zeros(n, np.uint32)
    deletion = np.zeros(n, np.int8)
    selector_bit = np.zeros(n, np.int32)
    delay_kind = np.zeros(n, np.int8)
    delay_a = np.zeros(n, np.float32)
    delay_b = np.zeros(n, np.float32)
    to_phase = np.zeros(n, np.int32)
    cond_assign = np.zeros(n, np.uint32)
    cond_value = np.zeros(n, np.uint32)
    is_delete = np.zeros(n, bool)
    weight = np.zeros(n, np.float32)

    for i, r in enumerate(mine):
        if r.weight < 0:
            raise ValueError(f"rule {r.name!r}: weight must be >= 0")
        weight[i] = float(r.weight)
        to_id = space.phase_id(r.effect.to_phase)
        if r.from_phases:
            mask = 0
            for p in r.from_phases:
                mask |= 1 << space.phase_id(p)
        else:
            # empty from_phases = match any phase (upstream Stage semantics
            # for an absent selector.matchPhases), EXCEPT the rule's own
            # target phase for non-delete rules — otherwise the rule re-fires
            # from the phase it just wrote, patching the apiserver forever.
            mask = 0xFFFFFFFF
            if not r.effect.delete:
                mask &= ~(1 << to_id) & 0xFFFFFFFF
        from_mask[i] = mask
        deletion[i] = np.int8(r.deletion)
        selector_bit[i] = selector_id(r.selector)
        delay_kind[i] = int(r.delay.kind)
        delay_a[i] = r.delay.a
        delay_b[i] = r.delay.b
        to_phase[i] = to_id
        ca = 0
        cv = 0
        for cond, val in r.effect.conditions.items():
            bit = 1 << space.condition_bit(cond)
            ca |= bit
            if val:
                cv |= bit
        cond_assign[i] = ca
        cond_value[i] = cv
        is_delete[i] = r.effect.delete

    return CompiledRules(
        resource=resource,
        space=space,
        from_mask=from_mask,
        deletion=deletion,
        selector_bit=selector_bit,
        delay_kind=delay_kind,
        delay_a=delay_a,
        delay_b=delay_b,
        to_phase=to_phase,
        cond_assign=cond_assign,
        cond_value=cond_value,
        is_delete=is_delete,
        weight=weight,
        names=tuple(r.name for r in mine),
        selector_names=tuple(selector_names),
    )


def match_rules_host(
    table: CompiledRules,
    phase: int,
    sel_bits: int,
    has_deletion: bool,
) -> list[int]:
    """All rule indices whose guards (phase mask, deletion requirement,
    selector bit) match, in priority order. Pure-python oracle mirror of
    the device-side [C, R] match in kwok_tpu.ops.tick."""
    out = []
    for i in range(table.num_rules):
        if not (int(table.from_mask[i]) >> phase) & 1:
            continue
        d = int(table.deletion[i])
        if d != DELETION_ANY and bool(d) != has_deletion:
            continue
        sb = int(table.selector_bit[i])
        if sb >= 0 and not (sel_bits >> sb) & 1:
            continue
        out.append(i)
    return out


def choose_rule_host(table: CompiledRules, matches: list[int], u2: float) -> int:
    """Select among matched rules exactly like the tick kernel:

    - no matches -> -1;
    - first match unweighted (weight 0) -> first match (deterministic);
    - first match weighted -> weighted-random among ALL matching weighted
      rules, P(i) proportional to weight[i], via the caller's uniform u2 in
      [0, 1) (the device uses its per-row PRNG draw).
    """
    if not matches:
        return -1
    first = matches[0]
    if float(table.weight[first]) <= 0:
        return first
    pool = [i for i in matches if float(table.weight[i]) > 0]
    total = sum(float(table.weight[i]) for i in pool)
    target = u2 * total
    acc = 0.0
    for i in pool:
        acc += float(table.weight[i])
        if acc > target:
            return i
    return pool[-1]


def match_rule_host(
    table: CompiledRules,
    phase: int,
    sel_bits: int,
    has_deletion: bool,
    u2: float = 0.0,
) -> int:
    """Pure-python single-row rule matcher (the oracle for property tests).

    Mirrors the device-side selection in kwok_tpu.ops.tick exactly: first
    rule (lowest index) whose guards all match — or, when the first match
    is weighted, the weighted draw made by `choose_rule_host` with `u2`
    (the default 0.0 picks the lowest-index weighted match)."""
    return choose_rule_host(
        table, match_rules_host(table, phase, sel_bits, has_deletion), u2
    )
