"""Default lifecycle rule sets: the behavior of the reference's templates.

Reference behavior being reproduced (pkg/kwok/controllers/...):
- Nodes: on observe, immediately patch status Ready with capacity defaults
  (node_controller.go:301-391 + templates/node.status.tpl), then refresh
  heartbeat conditions every 30s (node_controller.go:175-204; interval set at
  controller.go:118).
- Pods: on observe (already bound to a managed node — the scheduler did
  that), immediately patch status Running (pod_controller.go:205-231 +
  templates/pod.status.tpl).
- Pods with a deletionTimestamp: strip finalizers and delete with grace 0
  (pod_controller.go:155-183).

Heartbeats are NOT rules — they are a vectorized timer wheel in the tick
kernel (hb_due array), because they repeat rather than transition.
"""

from __future__ import annotations

from kwok_tpu.models.lifecycle import (
    DELETION_PRESENT,
    Delay,
    LifecycleRule,
    ResourceKind,
    StatusEffect,
)

# Selector names resolved by the host at ingest (kwok_tpu.engine):
# - "managed": passes the manage-selectors AND is not excluded by the
#   disregard-selectors (controller.go:81-111 + needLockNode/needLockPod).
#   For pods this additionally requires the bound node to be managed
#   (NodeHasFunc wiring, controller.go:137).
# - "on-managed-node" (pods): the bound node is managed, regardless of the
#   pod's own disregard annotations — the deletion path uses this
#   (pod_controller.go:306-316 gates deleteChan on nodeHasFunc only).
# - "heartbeat" (nodes): passes the manage-selectors (needHeartbeat,
#   node_controller.go:205-207); heartbeats ignore disregard.
SEL_MANAGED = "managed"
SEL_ON_MANAGED_NODE = "on-managed-node"
SEL_HEARTBEAT = "heartbeat"


def default_node_rules(ready_delay: Delay | None = None) -> list[LifecycleRule]:
    return [
        LifecycleRule(
            name="node-ready",
            resource=ResourceKind.NODE,
            from_phases=("Observed", "NotReady"),
            selector=SEL_MANAGED,
            delay=ready_delay or Delay.constant(0.0),
            effect=StatusEffect(
                to_phase="Ready",
                conditions={
                    "Ready": True,
                    "OutOfDisk": False,
                    "MemoryPressure": False,
                    "DiskPressure": False,
                    "NetworkUnavailable": False,
                    "PIDPressure": False,
                },
            ),
        ),
    ]


def default_pod_rules(running_delay: Delay | None = None) -> list[LifecycleRule]:
    return [
        # Deletion wins over everything (checked first, like the reference's
        # deleteChan taking DeletionTimestamp'd pods out of the lock path,
        # pod_controller.go:306-316).
        LifecycleRule(
            name="pod-delete",
            resource=ResourceKind.POD,
            from_phases=("Pending", "Running", "Succeeded", "Failed", "Terminating"),
            deletion=DELETION_PRESENT,
            selector=SEL_ON_MANAGED_NODE,
            delay=Delay.constant(0.0),
            effect=StatusEffect(to_phase="Gone", delete=True),
        ),
        LifecycleRule(
            name="pod-running",
            resource=ResourceKind.POD,
            from_phases=("Pending",),
            selector=SEL_MANAGED,
            delay=running_delay or Delay.constant(0.0),
            effect=StatusEffect(
                to_phase="Running",
                conditions={
                    "Initialized": True,
                    "Ready": True,
                    "ContainersReady": True,
                },
            ),
        ),
    ]


def default_rules() -> list[LifecycleRule]:
    return default_node_rules() + default_pod_rules()


def chaos_pod_rules(mean_run_seconds: float = 60.0) -> list[LifecycleRule]:
    """An example chaos rule set: pods run, then complete after Exp(mean).

    The BASELINE.json soak configs ("pod-chaos", Poisson delays) need
    stochastic transitions; constant-delay templates are the degenerate case.
    """
    rules = default_pod_rules()
    rules.append(
        LifecycleRule(
            name="pod-complete",
            resource=ResourceKind.POD,
            from_phases=("Running",),
            selector=SEL_MANAGED,
            delay=Delay.exponential(mean_run_seconds),
            effect=StatusEffect(
                to_phase="Succeeded",
                conditions={"Ready": False, "ContainersReady": False},
            ),
        )
    )
    return rules
