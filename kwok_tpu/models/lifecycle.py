"""Lifecycle rule IR: (match, delay, next-state) triples.

This is the framework's native lifecycle API. The reference's equivalent is
implicit: NodeController patches node status Ready immediately on observe
(pkg/kwok/controllers/node_controller.go:301-354), PodController patches pod
status Running (pod_controller.go:205-231), and deletion strips finalizers and
deletes (pod_controller.go:155-183). Each of those behaviors is one
`LifecycleRule` in the default rule set (kwok_tpu.models.defaults); users can
load their own rule sets from YAML (apiVersion kwok.x-k8s.io/v1alpha1, kind
Stage-compatible surface) to get delays, chaos, and custom state machines.

Design constraints for the TPU path:
- phases are small enums (<= 31 per resource kind) so a phase set fits a
  uint32 bitmask;
- selector matches are resolved on the HOST at ingest time into per-row
  selector bits (dynamic strings never reach the device);
- delays are distributions sampled on-device (constant / uniform /
  exponential) so Poisson-process chaos runs at full rate.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Mapping, Sequence


class ResourceKind(str, enum.Enum):
    NODE = "nodes"
    POD = "pods"


class DelayKind(enum.IntEnum):
    CONSTANT = 0
    UNIFORM = 1
    EXPONENTIAL = 2


@dataclasses.dataclass(frozen=True)
class Delay:
    """Delay before a matched rule fires.

    constant(v): fires exactly v seconds after match.
    uniform(a, b): U[a, b).
    exponential(mean, cap): Exp(mean), truncated at cap (cap<=0 -> uncapped).
    """

    kind: DelayKind = DelayKind.CONSTANT
    a: float = 0.0
    b: float = 0.0

    @staticmethod
    def constant(seconds: float = 0.0) -> "Delay":
        return Delay(DelayKind.CONSTANT, float(seconds), 0.0)

    @staticmethod
    def uniform(low: float, high: float) -> "Delay":
        return Delay(DelayKind.UNIFORM, float(low), float(high))

    @staticmethod
    def exponential(mean: float, cap: float = 0.0) -> "Delay":
        return Delay(DelayKind.EXPONENTIAL, float(mean), float(cap))


# Sentinel for "don't care" on the deletion-timestamp match.
DELETION_ANY = -1
DELETION_ABSENT = 0
DELETION_PRESENT = 1


@dataclasses.dataclass(frozen=True)
class StatusEffect:
    """What firing a rule does to a row.

    conditions maps condition-name -> True/False; names are resolved to bit
    positions by the compiler. The full status document (addresses, capacity,
    containerStatuses, ...) is rendered host-side at the API boundary from the
    row's (phase, condition bits) by kwok_tpu.edge.render — the device only
    tracks the decision-relevant state.
    """

    to_phase: str
    conditions: Mapping[str, bool] = dataclasses.field(default_factory=dict)
    # Emit a delete (not a status patch) when this rule fires — the analogue
    # of the reference's finalizer-strip + grace-0 delete
    # (pod_controller.go:155-183).
    delete: bool = False


@dataclasses.dataclass(frozen=True)
class LifecycleRule:
    """selector + delay + next-state: one edge of the lifecycle state machine.

    First matching rule wins (rules are ordered), unless the first match is
    weighted — see `weight` below for the stochastic-selection semantics. A
    row re-enters matching after every transition, so chains of rules
    express multi-step lifecycles (Pending -> Running -> Succeeded).
    """

    name: str
    resource: ResourceKind
    from_phases: Sequence[str]
    effect: StatusEffect
    delay: Delay = dataclasses.field(default_factory=Delay.constant)
    # DELETION_ANY / DELETION_ABSENT / DELETION_PRESENT
    deletion: int = DELETION_ABSENT
    # Name of a host-computed selector; resolved to a bit index by the
    # compiler. None => matches every row of the resource.
    selector: str | None = None
    # The Stage CRD's spec.weight. 0 (the default, = absent in YAML) keeps
    # the deterministic first-match-wins ordering. weight > 0 opts the rule
    # into stochastic selection: when the FIRST matching rule is weighted,
    # the row draws among ALL matching weighted rules with probability
    # proportional to weight (upstream Stage semantics for weighted stage
    # sets); a weight-0 rule at lower index still wins deterministically.
    # An armed choice is sticky — re-drawn only when ingest invalidates it
    # or the rule fires, never on a quiet tick.
    weight: int = 0


@dataclasses.dataclass(frozen=True)
class PhaseSpace:
    """Phase and condition vocabularies for one resource kind.

    Index 0 is the ingest phase (what a row starts as when first observed).
    """

    phases: tuple[str, ...]
    conditions: tuple[str, ...]

    def __post_init__(self) -> None:
        if len(self.phases) > 31:
            raise ValueError("at most 31 phases per resource kind")
        if len(self.conditions) > 32:
            raise ValueError("at most 32 condition bits per resource kind")

    def phase_id(self, name: str) -> int:
        return self.phases.index(name)

    def condition_bit(self, name: str) -> int:
        return self.conditions.index(name)


# --- canonical phase spaces -------------------------------------------------

# Node lifecycle. The reference only knows "unlocked" vs "locked (Ready)"
# (node_controller.go:301-354); we model that plus an explicit NotReady for
# chaos rules.
NODE_PHASES = PhaseSpace(
    phases=("Observed", "Ready", "NotReady", "Gone"),
    # Order matches pkg/kwok/controllers/templates/node.status.tpl condition
    # list (Ready, OutOfDisk, MemoryPressure, DiskPressure, NetworkUnavailable)
    # plus PIDPressure used by newer kubelets.
    conditions=(
        "Ready",
        "OutOfDisk",
        "MemoryPressure",
        "DiskPressure",
        "NetworkUnavailable",
        "PIDPressure",
    ),
)

# Pod lifecycle. Reference: Pending -> Running on lock
# (pod_controller.go:205-231, templates/pod.status.tpl), deletion ->
# finalizer-strip + delete (pod_controller.go:155-183).
POD_PHASES = PhaseSpace(
    phases=("Pending", "Running", "Succeeded", "Failed", "Terminating", "Gone"),
    # templates/pod.status.tpl conditions.
    conditions=("Initialized", "Ready", "ContainersReady", "PodScheduled"),
)

PHASE_SPACES: dict[ResourceKind, PhaseSpace] = {
    ResourceKind.NODE: NODE_PHASES,
    ResourceKind.POD: POD_PHASES,
}
