"""Structured logging (the pkg/log equivalent).

The reference wraps slog with a human TTY handler: colored level, message,
then dim `key=value` attributes (pkg/log/logger_ctl.go:78-139), a noop
logger, a `-v` verbosity flag (pkg/log/flags.go:26), and `KObj` object refs
(pkg/log/kobj.go:32). Here the same surface sits on stdlib logging:

    from kwok_tpu import log
    logger = log.get("kwok_tpu.engine")
    logger.info("node locked", node=log.kobj(node), elapsed=0.012)

renders (on a TTY, with color; plain otherwise):

    14:02:11 INFO  node locked  node=default/node-0 elapsed=0.012
"""

from kwok_tpu.log.logger import (
    KVLogger,
    add_flags,
    get,
    kobj,
    setup,
)

__all__ = ["KVLogger", "add_flags", "get", "kobj", "setup"]
