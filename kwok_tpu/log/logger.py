"""TTY-aware structured handler + kwargs logger + KObj refs."""

from __future__ import annotations

import logging
import sys
import time

_RESET = "\x1b[0m"
_DIM = "\x1b[2m"
_LEVEL_COLORS = {
    logging.DEBUG: "\x1b[36m",  # cyan
    logging.INFO: "\x1b[32m",  # green
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
    logging.CRITICAL: "\x1b[35m",  # magenta
}


def kobj(obj) -> str:
    """Compact object ref (pkg/log/kobj.go:32): `ns/name` or `name`."""
    meta = (obj or {}).get("metadata") or {} if isinstance(obj, dict) else {}
    name = meta.get("name") or "<unknown>"
    ns = meta.get("namespace")
    return f"{ns}/{name}" if ns else name


class HumanFormatter(logging.Formatter):
    """`HH:MM:SS LEVEL message  key=value ...` with color on a TTY
    (logger_ctl.go:78-139: colored level, dim attributes)."""

    def __init__(self, color: bool) -> None:
        super().__init__()
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        level = record.levelname
        msg = record.getMessage()
        kv = getattr(record, "kwok_kv", None)
        parts = []
        if self.color:
            c = _LEVEL_COLORS.get(record.levelno, "")
            parts.append(f"{ts} {c}{level:<5}{_RESET} {msg}")
            if kv:
                attrs = " ".join(f"{k}={_fmt(v)}" for k, v in kv.items())
                parts.append(f"  {_DIM}{attrs}{_RESET}")
        else:
            parts.append(f"{ts} {level:<5} {msg}")
            if kv:
                parts.append(
                    "  " + " ".join(f"{k}={_fmt(v)}" for k, v in kv.items())
                )
        out = "".join(parts)
        if record.exc_info:
            out += "\n" + self.formatException(record.exc_info)
        return out


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    s = str(v)
    if " " in s or not s:
        return repr(s)
    return s


class KVLogger:
    """Thin kwargs front-end: `log.info("msg", key=value)` attaches the
    attributes to the record for HumanFormatter (slog's AddAttrs shape
    without the reference's interface plumbing)."""

    def __init__(self, logger: logging.Logger) -> None:
        self._logger = logger

    def _log(self, level: int, msg: str, kv: dict, exc_info=None) -> None:
        if self._logger.isEnabledFor(level):
            self._logger.log(
                level, msg, extra={"kwok_kv": kv or None}, exc_info=exc_info
            )

    def debug(self, msg: str, **kv) -> None:
        self._log(logging.DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._log(logging.INFO, msg, kv)

    def warning(self, msg: str, **kv) -> None:
        self._log(logging.WARNING, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._log(logging.ERROR, msg, kv)

    def exception(self, msg: str, **kv) -> None:
        self._log(logging.ERROR, msg, kv, exc_info=True)


def get(name: str) -> KVLogger:
    return KVLogger(logging.getLogger(name))


def add_flags(parser) -> None:
    """The `-v` flag (flags.go:26): 0=info, >=1 debug."""
    parser.add_argument(
        "-v",
        "--verbosity",
        type=int,
        default=0,
        help="log verbosity: 0 info, >=1 debug",
    )


def setup(verbosity: int = 0, stream=None) -> None:
    """Install the human handler on the root logger (idempotent)."""
    stream = stream if stream is not None else sys.stderr
    color = hasattr(stream, "isatty") and stream.isatty()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(HumanFormatter(color))
    root = logging.getLogger()
    root.handlers = [
        h for h in root.handlers if not getattr(h, "_kwok_log", False)
    ]
    handler._kwok_log = True
    root.addHandler(handler)
    root.setLevel(logging.DEBUG if verbosity > 0 else logging.INFO)
