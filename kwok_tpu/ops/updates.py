"""Jitted scatter ops: host ingest writes -> device-resident state.

The cluster state stays resident on device between ticks (donated buffers);
the host never round-trips the full arrays. Watch events accumulate into
fixed-width padded batches (static shapes for XLA) and are scattered in:

- init_rows: (re)initialize whole rows — object created, row freed/recycled
- update_rows: modify the host-owned matching inputs of existing rows
  (sel_bits / has_deletion) without touching device-owned phase/cond/timers;
  the next tick's re-match logic notices any change (tick_body's
  `best != pending_rule` re-arm).

Padding uses idx = capacity (one past the end) with scatter mode='drop'.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kwok_tpu.ops.state import RowState

# Two fixed batch widths: each chunk pads to one of them (static shapes —
# at most two compiled variants per scatter). The LARGE width exists for
# remote/tunneled devices, where every dispatch pays client-side
# serialization + RPC: a 50k-row ingest wave costs 4 calls instead of 13.
# The SMALL width keeps single-event ticks from shipping a 16k-lane pad.
BATCH = int(os.environ.get("KWOK_TPU_FLUSH_BATCH", "4096"))
BATCH_LARGE = int(os.environ.get("KWOK_TPU_FLUSH_BATCH_LARGE", "16384"))


class InitBatch(NamedTuple):
    idx: np.ndarray  # int32[BATCH], capacity = padding
    active: np.ndarray  # bool
    phase: np.ndarray  # int32
    cond_bits: np.ndarray  # uint32
    sel_bits: np.ndarray  # uint32
    has_deletion: np.ndarray  # bool


class UpdateBatch(NamedTuple):
    idx: np.ndarray  # int32[BATCH], capacity = padding
    sel_bits: np.ndarray  # uint32
    has_deletion: np.ndarray  # bool


@functools.partial(jax.jit, donate_argnums=(0,))
def init_rows(state: RowState, b: InitBatch) -> RowState:
    idx = b.idx
    inf = jnp.float32(jnp.inf)
    return RowState(
        active=state.active.at[idx].set(b.active, mode="drop"),
        phase=state.phase.at[idx].set(b.phase, mode="drop"),
        cond_bits=state.cond_bits.at[idx].set(b.cond_bits, mode="drop"),
        sel_bits=state.sel_bits.at[idx].set(b.sel_bits, mode="drop"),
        has_deletion=state.has_deletion.at[idx].set(b.has_deletion, mode="drop"),
        pending_rule=state.pending_rule.at[idx].set(-1, mode="drop"),
        fire_at=state.fire_at.at[idx].set(inf, mode="drop"),
        hb_due=state.hb_due.at[idx].set(inf, mode="drop"),
        gen=state.gen.at[idx].set(0, mode="drop"),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def update_rows(state: RowState, b: UpdateBatch) -> RowState:
    idx = b.idx
    return state._replace(
        sel_bits=state.sel_bits.at[idx].set(b.sel_bits, mode="drop"),
        has_deletion=state.has_deletion.at[idx].set(b.has_deletion, mode="drop"),
    )


class UpdateBuffer:
    """Host-side accumulator that flushes padded batches to device."""

    def __init__(self) -> None:
        self._init: list[tuple[int, bool, int, int, int, bool]] = []
        self._upd: list[tuple[int, int, bool]] = []

    def stage_init(
        self,
        idx: int,
        active: bool,
        phase: int = 0,
        cond_bits: int = 0,
        sel_bits: int = 0,
        has_deletion: bool = False,
    ) -> None:
        self._init.append((idx, active, phase, cond_bits, sel_bits, has_deletion))

    def stage_update(self, idx: int, sel_bits: int, has_deletion: bool) -> None:
        self._upd.append((idx, sel_bits, has_deletion))

    @property
    def pending(self) -> int:
        return len(self._init) + len(self._upd)

    def flush(self, state: RowState, offset: int = 0) -> RowState:
        """Apply staged writes. `offset` shifts row indices (a cluster's slice
        of a federated stacked state). Padding lanes use the TARGET state's
        capacity as their index, which is always out of bounds under
        mode='drop' regardless of offset."""
        cap = state.capacity
        off = np.int32(offset)
        while self._init:
            width = BATCH_LARGE if len(self._init) > BATCH else BATCH
            chunk, self._init = self._init[:width], self._init[width:]
            n = len(chunk)
            pad = width - n
            b = InitBatch(
                idx=np.concatenate(
                    [np.fromiter((c[0] for c in chunk), np.int32, n) + off,
                     np.full(pad, cap, np.int32)]
                ),
                active=np.concatenate(
                    [np.fromiter((c[1] for c in chunk), bool, n), np.zeros(pad, bool)]
                ),
                phase=np.concatenate(
                    [np.fromiter((c[2] for c in chunk), np.int32, n),
                     np.zeros(pad, np.int32)]
                ),
                cond_bits=np.concatenate(
                    [np.fromiter((c[3] for c in chunk), np.uint32, n),
                     np.zeros(pad, np.uint32)]
                ),
                sel_bits=np.concatenate(
                    [np.fromiter((c[4] for c in chunk), np.uint32, n),
                     np.zeros(pad, np.uint32)]
                ),
                has_deletion=np.concatenate(
                    [np.fromiter((c[5] for c in chunk), bool, n), np.zeros(pad, bool)]
                ),
            )
            state = init_rows(state, b)
        while self._upd:
            width = BATCH_LARGE if len(self._upd) > BATCH else BATCH
            chunk, self._upd = self._upd[:width], self._upd[width:]
            n = len(chunk)
            pad = width - n
            b = UpdateBatch(
                idx=np.concatenate(
                    [np.fromiter((c[0] for c in chunk), np.int32, n) + off,
                     np.full(pad, cap, np.int32)]
                ),
                sel_bits=np.concatenate(
                    [np.fromiter((c[1] for c in chunk), np.uint32, n),
                     np.zeros(pad, np.uint32)]
                ),
                has_deletion=np.concatenate(
                    [np.fromiter((c[2] for c in chunk), bool, n), np.zeros(pad, bool)]
                ),
            )
            state = update_rows(state, b)
        return state
