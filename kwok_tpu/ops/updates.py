"""Jitted scatter ops: host ingest writes -> device-resident state.

The cluster state stays resident on device between ticks (donated buffers);
the host never round-trips the full arrays. Watch events accumulate into
fixed-width padded batches (static shapes for XLA) and are scattered in:

- init_rows: (re)initialize whole rows — object created, row freed/recycled
- update_rows: modify the host-owned matching inputs of existing rows
  (sel_bits / has_deletion) without touching device-owned phase/cond/timers;
  the next tick's re-match logic notices any change (tick_body's
  `best != pending_rule` re-arm).

Padding uses idx = capacity (one past the end) with scatter mode='drop'.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kwok_tpu.ops.state import RowState

# Two fixed batch widths: each chunk pads to one of them (static shapes —
# at most two compiled variants per scatter). The LARGE width exists for
# remote/tunneled devices, where every dispatch pays client-side
# serialization + RPC: a 50k-row ingest wave costs 4 calls instead of 13.
# The SMALL width keeps single-event ticks from shipping a 16k-lane pad.
BATCH = int(os.environ.get("KWOK_TPU_FLUSH_BATCH", "4096"))
BATCH_LARGE = int(os.environ.get("KWOK_TPU_FLUSH_BATCH_LARGE", "16384"))


class InitBatch(NamedTuple):
    idx: np.ndarray  # int32[BATCH], capacity = padding
    active: np.ndarray  # bool
    phase: np.ndarray  # int32
    cond_bits: np.ndarray  # uint32
    sel_bits: np.ndarray  # uint32
    has_deletion: np.ndarray  # bool


class UpdateBatch(NamedTuple):
    idx: np.ndarray  # int32[BATCH], capacity = padding
    sel_bits: np.ndarray  # uint32
    has_deletion: np.ndarray  # bool


@functools.partial(jax.jit, donate_argnums=(0,))
def init_rows(state: RowState, b: InitBatch) -> RowState:
    idx = b.idx
    inf = jnp.float32(jnp.inf)
    return RowState(
        active=state.active.at[idx].set(b.active, mode="drop"),
        phase=state.phase.at[idx].set(b.phase, mode="drop"),
        cond_bits=state.cond_bits.at[idx].set(b.cond_bits, mode="drop"),
        sel_bits=state.sel_bits.at[idx].set(b.sel_bits, mode="drop"),
        has_deletion=state.has_deletion.at[idx].set(b.has_deletion, mode="drop"),
        pending_rule=state.pending_rule.at[idx].set(-1, mode="drop"),
        fire_at=state.fire_at.at[idx].set(inf, mode="drop"),
        hb_due=state.hb_due.at[idx].set(inf, mode="drop"),
        gen=state.gen.at[idx].set(0, mode="drop"),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def update_rows(state: RowState, b: UpdateBatch) -> RowState:
    idx = b.idx
    return state._replace(
        sel_bits=state.sel_bits.at[idx].set(b.sel_bits, mode="drop"),
        has_deletion=state.has_deletion.at[idx].set(b.has_deletion, mode="drop"),
    )


class RefineBatch(NamedTuple):
    """Checkpoint-restore refinement: overwrite device-owned timer fields
    of already-armed rows (resilience/checkpoint.py). The tick kernel
    re-arms a restarted row with a FRESH delay; this scatter runs after
    that arming dispatch and restores the checkpointed residue, so an
    in-flight Stage delay resumes instead of resetting."""

    idx: np.ndarray  # int32[W], capacity = padding
    fire_at: np.ndarray  # float32
    hb_due: np.ndarray  # float32
    gen: np.ndarray  # int32


@functools.partial(jax.jit, donate_argnums=(0,))
def refine_rows(state: RowState, b: RefineBatch) -> RowState:
    idx = b.idx
    return state._replace(
        fire_at=state.fire_at.at[idx].set(b.fire_at, mode="drop"),
        hb_due=state.hb_due.at[idx].set(b.hb_due, mode="drop"),
        gen=state.gen.at[idx].set(b.gen, mode="drop"),
    )


def refine_flush(
    state: RowState,
    idx: np.ndarray,
    fire_at: np.ndarray,
    hb_due: np.ndarray,
    gen: np.ndarray,
    offset: int = 0,
) -> RowState:
    """Apply a refine run in the same fixed padded widths as the ingest
    scatters (two compiled variants, ever). ``offset`` shifts indices
    into a stacked state (lane/member slices); padding uses the target
    capacity under mode='drop', exactly like UpdateBuffer.flush."""
    cap = state.capacity
    off = np.int32(offset)
    n = int(idx.shape[0])
    pos = 0
    while pos < n:
        width = BATCH_LARGE if n - pos > BATCH else BATCH
        take = min(width, n - pos)
        pad = width - take
        sl = slice(pos, pos + take)
        b = RefineBatch(
            idx=np.concatenate(
                [np.asarray(idx[sl], np.int32) + off,
                 np.full(pad, cap, np.int32)]
            ),
            fire_at=np.concatenate(
                [np.asarray(fire_at[sl], np.float32),
                 np.zeros(pad, np.float32)]
            ),
            hb_due=np.concatenate(
                [np.asarray(hb_due[sl], np.float32),
                 np.zeros(pad, np.float32)]
            ),
            gen=np.concatenate(
                [np.asarray(gen[sl], np.int32), np.zeros(pad, np.int32)]
            ),
        )
        state = refine_rows(state, b)
        pos += take
    return state


class _InitBlock(NamedTuple):
    """A columnar run of active-row inits staged as whole arrays (the
    batched survivor-ingest path): one append instead of n tuple appends,
    and flush slices arrays instead of np.fromiter over tuples."""

    idx: np.ndarray  # int32
    phase: np.ndarray  # int32
    cond_bits: np.ndarray  # uint32
    sel_bits: np.ndarray  # uint32
    has_deletion: np.ndarray  # bool


class UpdateBuffer:
    """Host-side accumulator that flushes padded batches to device."""

    def __init__(self) -> None:
        # mixed per-row tuples and _InitBlock runs, in STAGING ORDER: a
        # row released (tuple init False) then re-acquired by a columnar
        # block (or vice versa) must flush in that order, or the stale
        # write wins on device
        self._init: list = []
        self._n_init = 0  # staged init ROWS (blocks count their length)
        self._upd: list[tuple[int, int, bool]] = []

    def stage_init(
        self,
        idx: int,
        active: bool,
        phase: int = 0,
        cond_bits: int = 0,
        sel_bits: int = 0,
        has_deletion: bool = False,
    ) -> None:
        self._init.append((idx, active, phase, cond_bits, sel_bits, has_deletion))
        self._n_init += 1

    def stage_init_array(
        self,
        idx: np.ndarray,
        phase,
        cond_bits: np.ndarray,
        sel_bits: np.ndarray,
        has_deletion: np.ndarray,
    ) -> None:
        """Stage a columnar run of ACTIVE row inits. `phase` may be a
        scalar (the survivor path: every new row starts Pending)."""
        n = int(idx.shape[0])
        if not n:
            return
        ph = np.asarray(phase, np.int32)
        if ph.ndim == 0:
            ph = np.full(n, ph, np.int32)
        self._init.append(_InitBlock(
            idx=np.ascontiguousarray(idx, np.int32),
            phase=ph,
            cond_bits=np.ascontiguousarray(cond_bits, np.uint32),
            sel_bits=np.ascontiguousarray(sel_bits, np.uint32),
            has_deletion=np.ascontiguousarray(has_deletion, bool),
        ))
        self._n_init += n

    def stage_update(self, idx: int, sel_bits: int, has_deletion: bool) -> None:
        self._upd.append((idx, sel_bits, has_deletion))

    def staged_rows(self) -> set:
        """Row indices with a staged-but-unflushed INIT. The checkpoint
        gather and restore refine (resilience/checkpoint.py) skip these:
        their device slots still describe a previous occupant (or
        nothing), so neither reading their timers nor overwriting them
        is meaningful until the init flushes. Updates are excluded on
        purpose — they only touch matching inputs, and the kernel's
        re-arm supersedes any refine on such rows at the next tick."""
        out: set = set()
        for entry in self._init:
            if isinstance(entry, _InitBlock):
                out.update(entry.idx.tolist())
            else:
                out.add(entry[0])
        return out

    @property
    def pending(self) -> int:
        return self._n_init + len(self._upd)

    @staticmethod
    def _flush_tuples(state: RowState, chunk: list, cap: int,
                      off: np.int32) -> RowState:
        while chunk:
            width = BATCH_LARGE if len(chunk) > BATCH else BATCH
            part, chunk = chunk[:width], chunk[width:]
            n = len(part)
            pad = width - n
            b = InitBatch(
                idx=np.concatenate(
                    [np.fromiter((c[0] for c in part), np.int32, n) + off,
                     np.full(pad, cap, np.int32)]
                ),
                active=np.concatenate(
                    [np.fromiter((c[1] for c in part), bool, n), np.zeros(pad, bool)]
                ),
                phase=np.concatenate(
                    [np.fromiter((c[2] for c in part), np.int32, n),
                     np.zeros(pad, np.int32)]
                ),
                cond_bits=np.concatenate(
                    [np.fromiter((c[3] for c in part), np.uint32, n),
                     np.zeros(pad, np.uint32)]
                ),
                sel_bits=np.concatenate(
                    [np.fromiter((c[4] for c in part), np.uint32, n),
                     np.zeros(pad, np.uint32)]
                ),
                has_deletion=np.concatenate(
                    [np.fromiter((c[5] for c in part), bool, n), np.zeros(pad, bool)]
                ),
            )
            state = init_rows(state, b)
        return state

    @staticmethod
    def _flush_block(state: RowState, blk: "_InitBlock", cap: int,
                     off: np.int32) -> RowState:
        n = int(blk.idx.shape[0])
        pos = 0
        while pos < n:
            width = BATCH_LARGE if n - pos > BATCH else BATCH
            take = min(width, n - pos)
            pad = width - take
            sl = slice(pos, pos + take)
            b = InitBatch(
                idx=np.concatenate(
                    [blk.idx[sl] + off, np.full(pad, cap, np.int32)]
                ),
                active=np.concatenate(
                    [np.ones(take, bool), np.zeros(pad, bool)]
                ),
                phase=np.concatenate(
                    [blk.phase[sl], np.zeros(pad, np.int32)]
                ),
                cond_bits=np.concatenate(
                    [blk.cond_bits[sl], np.zeros(pad, np.uint32)]
                ),
                sel_bits=np.concatenate(
                    [blk.sel_bits[sl], np.zeros(pad, np.uint32)]
                ),
                has_deletion=np.concatenate(
                    [blk.has_deletion[sl], np.zeros(pad, bool)]
                ),
            )
            state = init_rows(state, b)
            pos += take
        return state

    def flush(self, state: RowState, offset: int = 0) -> RowState:
        """Apply staged writes. `offset` shifts row indices (a cluster's slice
        of a federated stacked state). Padding lanes use the TARGET state's
        capacity as their index, which is always out of bounds under
        mode='drop' regardless of offset. Staged inits are cleared only
        after EVERY entry applied: on a mid-flush device error the caller
        discards the partially-applied state (RowState is functional), so
        the whole window stays staged and the next flush re-applies it
        from the start — row init is an idempotent overwrite, and the
        alternative (dropping consumed entries whose writes died with the
        raise) would strand acquired pool rows that never activate."""
        cap = state.capacity
        off = np.int32(offset)
        init = self._init
        pos = 0
        while pos < len(init):
            entry = init[pos]
            if isinstance(entry, _InitBlock):
                state = self._flush_block(state, entry, cap, off)
                pos += 1
            else:
                end = pos + 1
                while end < len(init) and not isinstance(
                    init[end], _InitBlock
                ):
                    end += 1
                state = self._flush_tuples(state, init[pos:end], cap, off)
                pos = end
        self._init = []
        self._n_init = 0
        while self._upd:
            width = BATCH_LARGE if len(self._upd) > BATCH else BATCH
            chunk, self._upd = self._upd[:width], self._upd[width:]
            n = len(chunk)
            pad = width - n
            b = UpdateBatch(
                idx=np.concatenate(
                    [np.fromiter((c[0] for c in chunk), np.int32, n) + off,
                     np.full(pad, cap, np.int32)]
                ),
                sel_bits=np.concatenate(
                    [np.fromiter((c[1] for c in chunk), np.uint32, n),
                     np.zeros(pad, np.uint32)]
                ),
                has_deletion=np.concatenate(
                    [np.fromiter((c[2] for c in chunk), bool, n), np.zeros(pad, bool)]
                ),
            )
            state = update_rows(state, b)
        return state
