"""Pure-numpy reference interpreter for the tick semantics.

The oracle for property tests (SURVEY.md section 4: "property-test the tick
kernel against a reference Python interpreter of the rules"). Implements the
same three steps as kwok_tpu.ops.tick.tick_body — match / fire / heartbeat —
in scalar-friendly numpy, reusing the single-row matcher and weighted-choice
helpers kwok_tpu.models.compiler.match_rules_host / choose_rule_host.

Randomness: the caller supplies the per-row uniform samples `u` (delay
sampling) and `u2` (weighted rule choice) so the oracle is deterministic;
tests use constant delays (u irrelevant) for exact equivalence and
statistical tests for the stochastic kinds.
"""

from __future__ import annotations

import numpy as np

from kwok_tpu.models.compiler import (
    CompiledRules,
    choose_rule_host,
    match_rules_host,
)
from kwok_tpu.models.lifecycle import DelayKind
from kwok_tpu.ops.state import RowState, TickOutputs


def _sample_delay(table: CompiledRules, rule: int, u: float) -> float:
    kind = int(table.delay_kind[rule])
    a = float(table.delay_a[rule])
    b = float(table.delay_b[rule])
    if kind == DelayKind.CONSTANT:
        return a
    if kind == DelayKind.UNIFORM:
        return a + (b - a) * u
    d = -a * float(np.log(u))
    if b > 0:
        d = min(d, b)
    return d


def reference_tick(
    state: RowState,
    now: float,
    table: CompiledRules,
    hb_interval: float = 30.0,
    hb_phase_mask: int = 0,
    hb_sel_bit: int = -1,
    u: np.ndarray | None = None,
    u2: np.ndarray | None = None,
) -> TickOutputs:
    c = state.capacity
    if u is None:
        u = np.full(c, 0.5)
    if u2 is None:
        u2 = np.zeros(c)

    phase = np.array(state.phase, np.int32)
    cond = np.array(state.cond_bits, np.uint32)
    pending = np.array(state.pending_rule, np.int32)
    fire_at = np.array(state.fire_at, np.float32)
    hb_due = np.array(state.hb_due, np.float32)
    gen = np.array(state.gen, np.int32)
    dirty = np.zeros(c, bool)
    deleted = np.zeros(c, bool)
    hb_fired = np.zeros(c, bool)
    transitions = 0

    for i in range(c):
        if not state.active[i]:
            # Match the kernel's writes on inactive rows: pending/fire_at/
            # hb_due are cleared (tick_body's where(active, ...) selects).
            pending[i] = -1
            fire_at[i] = np.inf
            hb_due[i] = np.inf
            continue
        # 1. match / re-arm. Sticky weighted choice mirrors the kernel: an
        # armed weighted rule that still matches is kept (no re-roll).
        matches = match_rules_host(
            table, int(phase[i]), int(state.sel_bits[i]),
            bool(state.has_deletion[i]),
        )
        p = int(pending[i])
        if (
            matches
            and float(table.weight[matches[0]]) > 0
            and p in matches
            and float(table.weight[p]) > 0
        ):
            best = p
        else:
            best = choose_rule_host(table, matches, float(u2[i]))
        if best != int(pending[i]):
            if best >= 0:
                pending[i] = best
                fire_at[i] = np.float32(now + _sample_delay(table, best, float(u[i])))
            else:
                pending[i] = -1
                fire_at[i] = np.inf
        # 2. fire
        if pending[i] >= 0 and now >= fire_at[i]:
            r = int(pending[i])
            phase[i] = table.to_phase[r]
            cond[i] = (cond[i] & ~table.cond_assign[r]) | table.cond_value[r]
            gen[i] += 1
            transitions += 1
            if table.is_delete[r]:
                deleted[i] = True
            else:
                dirty[i] = True
            pending[i] = -1
            fire_at[i] = np.inf
        # 3. heartbeat (same gating as tick_body)
        if hb_phase_mask == 0 and hb_sel_bit < 0:
            hb_on = False
        else:
            hb_on = True
            if hb_phase_mask != 0:
                hb_on = ((hb_phase_mask >> int(phase[i])) & 1) == 1
            if hb_on and hb_sel_bit >= 0:
                hb_on = ((int(state.sel_bits[i]) >> hb_sel_bit) & 1) == 1
        if not hb_on:
            hb_due[i] = np.inf
        else:
            if np.isinf(hb_due[i]):
                hb_due[i] = np.float32(now + hb_interval)
            elif now >= hb_due[i]:
                hb_fired[i] = True
                # schedule-anchored (Go time.Ticker): keep cadence when
                # late by < interval; re-anchor after a full-interval stall
                if now - hb_due[i] < hb_interval:
                    hb_due[i] = np.float32(hb_due[i] + hb_interval)
                else:
                    hb_due[i] = np.float32(now + hb_interval)

    new_state = RowState(
        active=np.array(state.active, bool),
        phase=phase,
        cond_bits=cond,
        sel_bits=np.array(state.sel_bits, np.uint32),
        has_deletion=np.array(state.has_deletion, bool),
        pending_rule=pending,
        fire_at=fire_at,
        hb_due=hb_due,
        gen=gen,
    )
    return TickOutputs(
        state=new_state,
        dirty=dirty,
        deleted=deleted,
        hb_fired=hb_fired,
        transitions=np.int32(transitions),
        heartbeats=np.int32(int(hb_fired.sum())),
    )
