"""Device-side engine ops: SoA cluster state + the jitted tick kernel.

This package replaces the reference's hot path — the per-object goroutine
reconcile loops in pkg/kwok/controllers/{node,pod}_controller.go — with one
batched state-transition kernel over struct-of-arrays tensors.
"""

from kwok_tpu.ops.state import RowState, TickOutputs, new_row_state
from kwok_tpu.ops.tick import TickKernel
from kwok_tpu.ops.reference import reference_tick

__all__ = [
    "RowState",
    "TickOutputs",
    "new_row_state",
    "TickKernel",
    "reference_tick",
]
