"""Experimental Pallas TPU kernel: K fused tick substeps with VMEM-resident
rows.

The XLA path (`ops/tick.py MultiTickKernel(steps=K)`) lax.scans K substeps;
the scan carry round-trips the full SoA row state through HBM every step —
~60 MB per step at 1M rows, ~7 GB per dispatch at K=120 (~9 ms of HBM
traffic at v5e bandwidth). This kernel instead grids over row blocks and
keeps each block in VMEM across ALL K substeps: one HBM read + one write
per row per dispatch, K× less state traffic. OPT-IN
(`KWOK_BENCH_PALLAS=1 python bench.py`). The round-5 like-for-like
crossover sweep on the real chip (BENCH_TPU_r05.json) measured this
kernel at 1.27-1.36x the XLA scan in its design regime — 16k-131k rows
at 120-240 substeps, where VMEM residency eliminates the scan carry's
HBM round-trips — and 0.84x at 1M rows (unpacked-mask D2H + grid
overhead outgrow the savings). The default path stays XLA for the 1M
headline; this kernel is the documented faster choice for small-to-mid
populations at deep substeps — see docs/architecture.md "Why Pallas is
opt-in".

Semantics are `ops/tick.py tick_body` exactly (match → re-arm → fire →
heartbeat wheel), with one documented divergence: delay sampling uses an
in-kernel counter-based hash RNG (finalizer-style integer mix over
(row, step, seed)) instead of jax.random's threefry stream — same
distributions, different stream, so constant-delay rule sets are
bit-identical to the XLA path and stochastic ones agree in distribution
(tests/test_pallas_tick.py pins both).

Layout: every field is viewed as [C/128, 128] (rows padded to a multiple of
block_rows*128 by the caller); bool fields travel as int32 so every ref
uses the f32/i32 (8,128) tile. Rule tables are tiny ([R], R < 32) and ride
along in SMEM; `now`/`seed` are scalar-prefetch style SMEM inputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from kwok_tpu.models.compiler import CompiledRules
from kwok_tpu.models.lifecycle import DelayKind
from kwok_tpu.ops.state import RowState, TickOutputs

LANES = 128
# numpy scalar, not a jnp array: pallas kernels may not capture
# concrete jax arrays as closure constants
INF = np.float32(np.inf)


def _mix(x: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer-style integer mix (xorshift-multiply), uint32 in/out."""
    x = x ^ (x >> 17)
    x = x * jnp.uint32(0xED5AD4BB)
    x = x ^ (x >> 11)
    x = x * jnp.uint32(0xAC4C1B51)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x31848BAB)
    x = x ^ (x >> 14)
    return x


def _uniform01(gid: jnp.ndarray, step: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """u in [1e-7, 1) from (row id, step, seed) — the kernel's stand-in for
    tick_body's jax.random.uniform(minval=1e-7)."""
    h = _mix(gid ^ (step.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)) ^ seed)
    # top 23 bits -> mantissa of a float in [1, 2), minus 1 -> [0, 1)
    f = jax.lax.bitcast_convert_type(
        (h >> 9) | jnp.uint32(0x3F800000), jnp.float32
    ) - jnp.float32(1.0)
    return jnp.maximum(f, jnp.float32(1e-7))


def _kernel(
    # --- SMEM scalars -----------------------------------------------------
    now_ref, seed_ref,
    fm_ref, del_ref, selbit_ref, dk_ref, da_ref, db_ref,
    tp_ref, ca_ref, cv_ref, isdel_ref, w_ref,
    # --- row blocks (VMEM) ------------------------------------------------
    active_ref, phase_ref, cond_ref, selb_ref, hasdel_ref,
    pend_ref, fire_ref, hb_ref, gen_ref,
    # --- outputs ----------------------------------------------------------
    o_phase, o_cond, o_pend, o_fire, o_hb, o_gen,
    o_dirty, o_deleted, o_hbf, o_counts,
    *,
    num_rules: int,
    steps: int,
    dt: float,
    hb_interval: float,
    hb_phase_mask: int,
    hb_sel_bit: int,
    block_rows: int,
    has_weights: bool,
):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    active = active_ref[:] != 0
    has_deletion = hasdel_ref[:] != 0
    sel_bits = selb_ref[:].astype(jnp.uint32)

    # global row id for the RNG stream
    r_iota = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, LANES), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, LANES), 1)
    gid = (
        (jnp.uint32(i) * jnp.uint32(block_rows) + r_iota) * jnp.uint32(LANES)
        + c_iota
    )
    seed = seed_ref[0].astype(jnp.uint32)
    now0 = now_ref[0]

    zero_b = jnp.zeros((block_rows, LANES), jnp.bool_)

    def step_fn(s, carry):
        (phase, cond, pend, fire, hb_due, gen,
         dirty_acc, del_acc, hbf_acc, trans, hbs) = carry
        now = now0 + s.astype(jnp.float32) * jnp.float32(dt)

        if num_rules > 0:
            phase_u = phase.astype(jnp.uint32)
            best = jnp.full((block_rows, LANES), -1, jnp.int32)
            matches = []
            # R is static and tiny: unrolled first-match-wins scan
            for r in range(num_rules):
                phase_ok = ((fm_ref[r].astype(jnp.uint32) >> phase_u) & 1) == 1
                dmode = del_ref[r]
                del_ok = (dmode == -1) | ((dmode == 1) == has_deletion)
                sbit = selbit_ref[r]
                sel_ok = (sbit < 0) | (
                    ((sel_bits >> jnp.maximum(sbit, 0).astype(jnp.uint32)) & 1)
                    == 1
                )
                m = active & phase_ok & del_ok & sel_ok
                matches.append(m)
                best = jnp.where((best < 0) & m, jnp.int32(r), best)

            if has_weights:
                # Stage spec.weight (mirrors tick_body): when the FIRST
                # matching rule is weighted, draw among ALL matching
                # weighted rules with P(i) ~ w[i]; an armed weighted
                # choice is STICKY while it still matches. Two unrolled
                # passes (total, then cumulative-vs-target); a zero-mass
                # rule can never be chosen (its cumsum step is flat).
                zf = jnp.zeros((block_rows, LANES), jnp.float32)
                total = zf
                for r in range(num_rules):
                    total = total + jnp.where(matches[r], w_ref[r], 0.0)
                u2 = _uniform01(gid, s, seed ^ jnp.uint32(0x55AA55AA))
                target = u2 * total
                cum = zf
                chosen = jnp.full((block_rows, LANES), -1, jnp.int32)
                wbest = zf
                wpend = zf
                pend_m = zero_b
                for r in range(num_rules):
                    cum = cum + jnp.where(matches[r], w_ref[r], 0.0)
                    chosen = jnp.where(
                        (chosen < 0) & (cum > target), jnp.int32(r), chosen
                    )
                    wbest = jnp.where(best == r, w_ref[r], wbest)
                    psel = pend == r
                    pend_m = pend_m | (psel & matches[r])
                    wpend = jnp.where(psel, w_ref[r], wpend)
                use_weighted = (best >= 0) & (wbest > 0)
                pend_valid = (pend >= 0) & pend_m & (wpend > 0)
                best = jnp.where(
                    use_weighted,
                    jnp.where(pend_valid, pend, chosen),
                    best,
                )

            rearm = active & (best != pend) & (best >= 0)
            # delay sampling: gather rule params by best (tiny R: select)
            rid = jnp.maximum(best, 0)
            dk = jnp.zeros((block_rows, LANES), jnp.int32)
            a = jnp.zeros((block_rows, LANES), jnp.float32)
            b = jnp.zeros((block_rows, LANES), jnp.float32)
            for r in range(num_rules):
                sel = rid == r
                dk = jnp.where(sel, dk_ref[r], dk)
                a = jnp.where(sel, da_ref[r], a)
                b = jnp.where(sel, db_ref[r], b)
            u = _uniform01(gid, s, seed)
            d_uniform = a + (b - a) * u
            d_exp = -a * jnp.log(u)
            d_exp = jnp.where(b > 0, jnp.minimum(d_exp, b), d_exp)
            delay = jnp.where(
                dk == int(DelayKind.CONSTANT),
                a,
                jnp.where(dk == int(DelayKind.UNIFORM), d_uniform, d_exp),
            )
            pend = jnp.where(active, best, jnp.int32(-1))
            fire = jnp.where(
                rearm, now + delay, jnp.where(pend >= 0, fire, INF)
            )

            can_fire = active & (pend >= 0) & (now >= fire)
            frid = jnp.maximum(pend, 0)
            tp = jnp.zeros((block_rows, LANES), jnp.int32)
            ca = jnp.zeros((block_rows, LANES), jnp.uint32)
            cv = jnp.zeros((block_rows, LANES), jnp.uint32)
            # isdel stays int32 through the select chain: broadcasting the
            # SMEM scalar as a bool (isdel_ref[r] != 0) makes Mosaic
            # truncate i32->i1, which it cannot lower (first hardware run
            # caught this; interpret mode doesn't lower through Mosaic)
            isdel = jnp.zeros((block_rows, LANES), jnp.int32)
            for r in range(num_rules):
                sel = frid == r
                tp = jnp.where(sel, tp_ref[r], tp)
                ca = jnp.where(sel, ca_ref[r].astype(jnp.uint32), ca)
                cv = jnp.where(sel, cv_ref[r].astype(jnp.uint32), cv)
                isdel = jnp.where(sel, isdel_ref[r], isdel)
            fired_delete = can_fire & (isdel != 0)
            phase = jnp.where(can_fire, tp, phase)
            cond = jnp.where(can_fire, (cond & ~ca) | cv, cond)
            pend = jnp.where(can_fire, jnp.int32(-1), pend)
            fire = jnp.where(can_fire, INF, fire)
            gen = gen + can_fire.astype(jnp.int32)
            dirty = can_fire & ~fired_delete
        else:
            can_fire = zero_b
            dirty = zero_b
            fired_delete = zero_b

        # heartbeat wheel (gating mirrors tick_body exactly)
        if hb_phase_mask == 0 and hb_sel_bit < 0:
            hb_on = zero_b
        else:
            hb_on = active
            if hb_phase_mask != 0:
                hb_on = hb_on & (
                    ((jnp.uint32(hb_phase_mask) >> phase.astype(jnp.uint32))
                     & 1) == 1
                )
            if hb_sel_bit >= 0:
                hb_on = hb_on & (
                    ((sel_bits >> jnp.uint32(hb_sel_bit)) & 1) == 1
                )
        entered = hb_on & jnp.isinf(hb_due)
        hb_fired = hb_on & (now >= hb_due)
        # schedule-anchored cadence, matching tick_body (Go time.Ticker
        # semantics): late-by-<interval fires keep their schedule
        ivl = jnp.float32(hb_interval)
        on_schedule = now - hb_due < ivl
        hb_due = jnp.where(
            ~hb_on,
            INF,
            jnp.where(
                entered,
                now + ivl,
                jnp.where(
                    hb_fired,
                    jnp.where(on_schedule, hb_due + ivl, now + ivl),
                    hb_due,
                ),
            ),
        )

        # accumulator masks travel as int32: Mosaic cannot legalize an
        # scf.for whose carry holds i1 vectors (first hardware run caught
        # this — "Unsupported target bitwidth for truncation"; interpret
        # mode doesn't lower through Mosaic)
        return (
            phase, cond, pend, fire, hb_due, gen,
            dirty_acc | dirty.astype(jnp.int32),
            del_acc | fired_delete.astype(jnp.int32),
            hbf_acc | hb_fired.astype(jnp.int32),
            trans + can_fire.sum(dtype=jnp.int32),
            hbs + hb_fired.sum(dtype=jnp.int32),
        )

    zero_i = jnp.zeros((block_rows, LANES), jnp.int32)
    init = (
        phase_ref[:], cond_ref[:].astype(jnp.uint32), pend_ref[:],
        fire_ref[:], hb_ref[:], gen_ref[:],
        zero_i, zero_i, zero_i, jnp.int32(0), jnp.int32(0),
    )
    (phase, cond, pend, fire, hb_due, gen,
     dirty, deleted, hbf, trans, hbs) = jax.lax.fori_loop(
        0, steps, step_fn, init
    )

    o_phase[:] = phase
    o_cond[:] = cond
    o_pend[:] = pend
    o_fire[:] = fire
    o_hb[:] = hb_due
    o_gen[:] = gen
    o_dirty[:] = dirty
    o_deleted[:] = deleted
    o_hbf[:] = hbf
    # counters ride out as a full (8, 128) i32 tile: Mosaic requires the
    # last two block dims to be (8, 128)-divisible even in SMEM (first
    # hardware run caught this; interpret mode doesn't lower through
    # Mosaic), so the 2 scalars sit in lanes (0,0)/(0,1) of a padded tile
    r_i = jax.lax.broadcasted_iota(jnp.int32, (8, LANES), 0)
    c_i = jax.lax.broadcasted_iota(jnp.int32, (8, LANES), 1)
    o_counts[0] = jnp.where(
        (r_i == 0) & (c_i == 0),
        trans,
        jnp.where((r_i == 0) & (c_i == 1), hbs, jnp.int32(0)),
    )


class PallasTickKernel:
    """K fused substeps for ONE resource kind, rows resident in VMEM.

    Drop-in for `TickKernel` at the `MultiTickKernel(steps=K)` semantics:
    `__call__(state, now)` advances K substeps of `dt` starting at `now`
    and returns TickOutputs with OR'd masks and summed counters — the same
    contract the engine's emit consumes.
    """

    def __init__(
        self,
        table: CompiledRules,
        hb_interval: float = 30.0,
        hb_phases: tuple[str, ...] = (),
        hb_sel_bit: int = -1,
        steps: int = 1,
        dt: float = 0.0,
        block_rows: int = 8,
        interpret: bool = False,
    ) -> None:
        self.table = table
        # trace-time constant: unweighted tables (every default set)
        # compile to exactly the pre-weight program, like tick_body
        self.has_weights = bool((np.asarray(table.weight) > 0).any())
        self.steps = int(steps)
        self.dt = float(dt)
        self.block_rows = int(block_rows)
        self.interpret = bool(interpret)
        mask = 0
        for p in hb_phases:
            mask |= 1 << table.space.phase_id(p)
        self.hb_phase_mask = mask
        self.hb_sel_bit = int(hb_sel_bit)
        self.hb_interval = float(hb_interval)
        self._rules_host = table
        self._seed = np.uint32(0x5EEDC0DE)
        self._step_n = 0
        self._compiled = None

    # ----------------------------------------------------------- plumbing

    def _build(self, capacity: int):
        import jax.experimental.pallas as pl

        t = self._rules_host
        R = len(t.from_mask)
        br = self.block_rows
        assert capacity % (br * LANES) == 0, (
            f"capacity {capacity} must be a multiple of {br * LANES}"
        )
        grid = capacity // (br * LANES)
        shape2 = (capacity // LANES, LANES)

        try:
            from jax.experimental.pallas import tpu as pltpu

            smem = pltpu.SMEM
        # kwoklint: disable=silent-except -- backend-dependent import probe: pltpu is absent or broken on cpu-only installs and smem=None falls back to the default memory space
        except Exception:  # pragma: no cover - cpu-only installs
            smem = None

        def spec_scalar(n):
            if smem is None:
                return pl.BlockSpec(memory_space=None)
            return pl.BlockSpec(memory_space=smem)

        row_spec = pl.BlockSpec((br, LANES), lambda i: (i, 0))
        kern = functools.partial(
            _kernel,
            num_rules=R,
            steps=self.steps,
            dt=self.dt,
            hb_interval=self.hb_interval,
            hb_phase_mask=self.hb_phase_mask,
            hb_sel_bit=self.hb_sel_bit,
            block_rows=br,
            has_weights=self.has_weights,
        )
        i32 = jnp.int32
        out_shapes = [
            jax.ShapeDtypeStruct(shape2, i32),        # phase
            jax.ShapeDtypeStruct(shape2, jnp.uint32), # cond
            jax.ShapeDtypeStruct(shape2, i32),        # pend
            jax.ShapeDtypeStruct(shape2, jnp.float32),# fire
            jax.ShapeDtypeStruct(shape2, jnp.float32),# hb_due
            jax.ShapeDtypeStruct(shape2, i32),        # gen
            jax.ShapeDtypeStruct(shape2, i32),        # dirty
            jax.ShapeDtypeStruct(shape2, i32),        # deleted
            jax.ShapeDtypeStruct(shape2, i32),        # hbf
            # per-block counters, padded to a full tile (see _kernel)
            jax.ShapeDtypeStruct((grid, 8, LANES), i32),
        ]
        out_specs = [row_spec] * 9 + [
            pl.BlockSpec((1, 8, LANES), lambda i: (i, 0, 0))
        ]
        in_specs = (
            [spec_scalar(1)] * 2       # now, seed
            + [spec_scalar(R)] * 11    # rule arrays (incl. weight)
            + [row_spec] * 9           # state blocks
        )
        call = pl.pallas_call(
            kern,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=self.interpret,
        )

        rules = (
            jnp.asarray(t.from_mask, jnp.uint32),
            jnp.asarray(t.deletion, jnp.int32),
            jnp.asarray(t.selector_bit, jnp.int32),
            jnp.asarray(t.delay_kind, jnp.int32),
            jnp.asarray(t.delay_a, jnp.float32),
            jnp.asarray(t.delay_b, jnp.float32),
            jnp.asarray(t.to_phase, jnp.int32),
            jnp.asarray(t.cond_assign, jnp.uint32),
            jnp.asarray(t.cond_value, jnp.uint32),
            jnp.asarray(t.is_delete, jnp.int32),
            jnp.asarray(t.weight, jnp.float32),
        )

        def run(state: RowState, now, seed):
            r2 = lambda a, dt_: a.astype(dt_).reshape(shape2)  # noqa: E731
            outs = call(
                jnp.asarray([now], jnp.float32),
                jnp.asarray([seed], jnp.uint32),
                *rules,
                r2(state.active, jnp.int32),
                r2(state.phase, jnp.int32),
                r2(state.cond_bits, jnp.uint32),
                r2(state.sel_bits, jnp.uint32),
                r2(state.has_deletion, jnp.int32),
                r2(state.pending_rule, jnp.int32),
                r2(state.fire_at, jnp.float32),
                r2(state.hb_due, jnp.float32),
                r2(state.gen, jnp.int32),
            )
            (phase, cond, pend, fire, hb_due, gen,
             dirty, deleted, hbf, counts) = outs
            flat = lambda a: a.reshape(capacity)  # noqa: E731
            new_state = RowState(
                active=state.active,
                phase=flat(phase),
                cond_bits=flat(cond),
                sel_bits=state.sel_bits,
                has_deletion=state.has_deletion,
                pending_rule=flat(pend),
                fire_at=flat(fire),
                hb_due=flat(hb_due),
                gen=flat(gen),
            )
            return TickOutputs(
                state=new_state,
                dirty=flat(dirty) != 0,
                deleted=flat(deleted) != 0,
                hb_fired=flat(hbf) != 0,
                transitions=counts[:, 0, 0].sum(dtype=jnp.int32),
                heartbeats=counts[:, 0, 1].sum(dtype=jnp.int32),
            )

        return run

    def raw_step(self, capacity: int):
        """The UNJITTED step function (state, now, seed) -> TickOutputs —
        for callers composing several kernels under one jit (bench.py's
        pallas mode fuses pods+nodes into a single dispatch this way)."""
        return self._build(capacity)

    def __call__(self, state: RowState, now: float) -> TickOutputs:
        cap = int(state.active.shape[0])
        if self._compiled is None or self._cap != cap:
            self._compiled = jax.jit(self._build(cap))
            self._cap = cap
        self._step_n += 1
        return self._compiled(
            state, jnp.float32(now), np.uint32(self._seed + self._step_n)
        )
