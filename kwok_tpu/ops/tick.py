"""The jitted tick kernel.

One call advances an entire resource population one step:

  1. (re)match: every active row is matched against the compiled rule table
     (first match wins). A row whose best rule CHANGED since last tick —
     because ingest updated its phase / deletionTimestamp / selector bits —
     is re-armed with a freshly sampled delay. This replaces the reference's
     event-driven channels (watch event -> chan -> worker,
     pkg/kwok/controllers/node_controller.go:301-354,
     pod_controller.go:205-250): ingest only writes row fields; the next tick
     notices.
  2. fire: rows whose pending rule's fire-time has arrived transition: phase
     and condition bits update, generation bumps, and the row lands in the
     dirty mask (status patch needed) or deleted mask (API delete needed,
     the analogue of pod_controller.go:155-183).
  3. heartbeat: a vectorized timer wheel replaces KeepNodeHeartbeat's
     snapshot-sort-fanout over a 16-worker pool
     (node_controller.go:175-204): rows in heartbeat-enabled phases with
     hb_due <= now land in the hb_fired mask and get hb_due += interval.

Everything is branch-free jnp; the whole function jits to one XLA program.
Matching broadcasts a [C, R] boolean — R (rule count) is tiny (<32), so this
stays bandwidth-bound on the row arrays, which is the right regime for TPU.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kwok_tpu.models.compiler import CompiledRules
from kwok_tpu.models.lifecycle import DelayKind
from kwok_tpu.ops.state import RowState, TickOutputs

INF = jnp.float32(jnp.inf)

# Engine time is f32 (TPU-native width). Past 2**17 s (~36h) the ulp grows
# beyond 2**-6 s and heartbeat/delay quantization would creep; the engine
# rebases its epoch (rebase_times + epoch shift on the host clock) before
# `now` ever crosses this, keeping sub-16ms resolution for unbounded uptimes.
# Env-overridable so endurance rigs can force several rebases per hour
# (benchmarks/endurance.py) instead of waiting ~36h for the first.
REBASE_AFTER = float(os.environ.get("KWOK_TPU_REBASE_AFTER", "") or 131072.0)


@jax.jit
def rebase_times(state: RowState, shift: jnp.ndarray) -> RowState:
    """Shift the engine-time fields down by `shift` seconds (epoch rebase).
    +inf sentinels are preserved (inf - finite == inf). One fused elementwise
    pass; sharding of the inputs is preserved under jit."""
    s = jnp.float32(shift)
    return state._replace(
        fire_at=state.fire_at - s, hb_due=state.hb_due - s
    )


def _rule_arrays(table: CompiledRules) -> dict[str, jnp.ndarray]:
    return {
        "from_mask": jnp.asarray(table.from_mask, jnp.uint32),
        "deletion": jnp.asarray(table.deletion, jnp.int8),
        "selector_bit": jnp.asarray(table.selector_bit, jnp.int32),
        "delay_kind": jnp.asarray(table.delay_kind, jnp.int8),
        "delay_a": jnp.asarray(table.delay_a, jnp.float32),
        "delay_b": jnp.asarray(table.delay_b, jnp.float32),
        "to_phase": jnp.asarray(table.to_phase, jnp.int32),
        "cond_assign": jnp.asarray(table.cond_assign, jnp.uint32),
        "cond_value": jnp.asarray(table.cond_value, jnp.uint32),
        "is_delete": jnp.asarray(table.is_delete, bool),
        "weight": jnp.asarray(table.weight, jnp.float32),
        # python bool, decided at compile time: unweighted tables (every
        # default set) trace to exactly the pre-weight program
        "has_weights": bool(np.asarray(table.weight).max(initial=0.0) > 0),
    }


def tick_body(
    state: RowState,
    now: jnp.ndarray,
    key: jax.Array,
    rules: dict[str, jnp.ndarray],
    hb_interval: float,
    hb_phase_mask: int,
    hb_sel_bit: int = -1,
) -> TickOutputs:
    """Pure tick function — shared by the single-device jit and shard_map."""
    capacity = state.active.shape[0]
    num_rules = rules["from_mask"].shape[0]

    active = state.active
    phase = state.phase

    if num_rules > 0:
        # --- 1. match ------------------------------------------------------
        phase_u = phase.astype(jnp.uint32)
        phase_ok = ((rules["from_mask"][None, :] >> phase_u[:, None]) & 1) == 1
        deletion = rules["deletion"][None, :].astype(jnp.int32)
        del_ok = (deletion == -1) | (
            (deletion == 1) == state.has_deletion[:, None]
        )
        sel_bit = rules["selector_bit"][None, :]
        sel_ok = (sel_bit < 0) | (
            ((state.sel_bits[:, None] >> jnp.maximum(sel_bit, 0).astype(jnp.uint32)) & 1) == 1
        )
        match = phase_ok & del_ok & sel_ok  # [C, R]
        any_match = match.any(axis=1)
        first = jnp.argmax(match, axis=1).astype(jnp.int32)  # first True

        # Stage spec.weight (LifecycleRule.weight): when the FIRST matching
        # rule is weighted, draw among ALL matching weighted rules with
        # P(i) ~ weight[i]; an armed weighted choice is STICKY (kept while
        # it still matches) so quiet ticks never re-roll. `has_weights` is
        # a trace-time constant — unweighted tables (the default sets)
        # compile to exactly the pre-weight program.
        w = rules["weight"]
        has_weights = rules["has_weights"]
        key_delay = key
        if has_weights:
            key_delay = jax.random.fold_in(key, 0)
            wm = match.astype(jnp.float32) * w[None, :]
            cw = jnp.cumsum(wm, axis=1)
            total = cw[:, -1]
            u2 = jax.random.uniform(
                jax.random.fold_in(key, 1), (capacity,), jnp.float32,
                minval=1e-7, maxval=1.0,
            )
            # first index whose cumulative weight exceeds the target; a
            # zero-mass rule can never be chosen (its cumsum step is flat)
            chosen = jnp.argmax(
                cw > (u2 * total)[:, None], axis=1
            ).astype(jnp.int32)
            use_weighted = any_match & (w[first] > 0)
            pend = state.pending_rule
            pidx = jnp.maximum(pend, 0)
            pend_valid = (pend >= 0) & jnp.take_along_axis(
                match, pidx[:, None], axis=1
            )[:, 0] & (w[pidx] > 0)
            first = jnp.where(
                use_weighted, jnp.where(pend_valid, pend, chosen), first
            )
        best = jnp.where(active & any_match, first, jnp.int32(-1))

        # Re-arm rows whose best rule changed (covers newly matched rows and
        # rows invalidated by ingest writes).
        rearm = active & (best != state.pending_rule) & (best >= 0)
        rid = jnp.maximum(best, 0)
        dk = rules["delay_kind"][rid].astype(jnp.int32)
        a = rules["delay_a"][rid]
        b = rules["delay_b"][rid]
        u = jax.random.uniform(
            key_delay, (capacity,), jnp.float32, minval=1e-7, maxval=1.0
        )
        d_uniform = a + (b - a) * u
        d_exp = -a * jnp.log(u)
        d_exp = jnp.where(b > 0, jnp.minimum(d_exp, b), d_exp)
        delay = jnp.where(
            dk == DelayKind.CONSTANT,
            a,
            jnp.where(dk == DelayKind.UNIFORM, d_uniform, d_exp),
        )
        pending = jnp.where(active, best, jnp.int32(-1))
        fire_at = jnp.where(
            rearm, now + delay, jnp.where(pending >= 0, state.fire_at, INF)
        )

        # --- 2. fire -------------------------------------------------------
        can_fire = active & (pending >= 0) & (now >= fire_at)
        frid = jnp.maximum(pending, 0)
        fired_delete = can_fire & rules["is_delete"][frid]
        new_phase = jnp.where(can_fire, rules["to_phase"][frid], phase)
        assign = rules["cond_assign"][frid]
        value = rules["cond_value"][frid]
        new_cond = jnp.where(
            can_fire, (state.cond_bits & ~assign) | value, state.cond_bits
        )
        pending = jnp.where(can_fire, jnp.int32(-1), pending)
        fire_at = jnp.where(can_fire, INF, fire_at)
        new_gen = state.gen + can_fire.astype(jnp.int32)
        dirty = can_fire & ~fired_delete
    else:
        new_phase = phase
        new_cond = state.cond_bits
        pending = state.pending_rule
        fire_at = state.fire_at
        new_gen = state.gen
        can_fire = jnp.zeros(capacity, bool)
        dirty = can_fire
        fired_delete = can_fire

    # --- 3. heartbeat wheel ------------------------------------------------
    # Gating: by phase set (hb_phase_mask; 0 = every phase) and/or by a
    # selector bit (hb_sel_bit; reference semantics: every node passing the
    # manage-selectors heartbeats, even disregarded ones —
    # node_controller.go:205-207 needHeartbeat vs needLockNode). Disabled
    # entirely when both are "match nothing" (mask 0 and bit -1).
    if hb_phase_mask == 0 and hb_sel_bit < 0:
        hb_on = jnp.zeros_like(active)
    else:
        hb_on = active
        if hb_phase_mask != 0:
            hb_mask = jnp.uint32(hb_phase_mask)
            hb_on = hb_on & (((hb_mask >> new_phase.astype(jnp.uint32)) & 1) == 1)
        if hb_sel_bit >= 0:
            hb_on = hb_on & (
                ((state.sel_bits >> jnp.uint32(hb_sel_bit)) & 1) == 1
            )
    entered = hb_on & jnp.isinf(state.hb_due)
    hb_fired = hb_on & (now >= state.hb_due)
    # Schedule-anchored cadence (Go time.Ticker semantics, matching the
    # reference's heartbeat loop): a fire that ran late by < interval
    # keeps its original schedule (due += interval) so per-dispatch
    # jitter does not accumulate into cadence drift; a stall of >= one
    # interval re-anchors at now + interval instead of bursting catch-up
    # beats.
    ivl = jnp.float32(hb_interval)
    on_schedule = now - state.hb_due < ivl
    hb_due = jnp.where(
        ~hb_on,
        INF,
        jnp.where(
            entered,
            now + ivl,
            jnp.where(
                hb_fired,
                jnp.where(on_schedule, state.hb_due + ivl, now + ivl),
                state.hb_due,
            ),
        ),
    )

    new_state = RowState(
        active=active,
        phase=new_phase,
        cond_bits=new_cond,
        sel_bits=state.sel_bits,
        has_deletion=state.has_deletion,
        pending_rule=pending,
        fire_at=fire_at,
        hb_due=hb_due,
        gen=new_gen,
    )
    return TickOutputs(
        state=new_state,
        dirty=dirty,
        deleted=fired_delete,
        hb_fired=hb_fired,
        transitions=can_fire.sum(dtype=jnp.int32),
        heartbeats=hb_fired.sum(dtype=jnp.int32),
    )


def next_due(state: RowState) -> jnp.ndarray:
    """Engine-time of the earliest pending timer (rule fire or heartbeat)
    across all active rows; +inf when nothing is scheduled. Lets the host
    tick loop SLEEP instead of dispatching no-op ticks — an idle engine
    (even at 1M rows) costs zero device work until the next deadline."""
    armed = state.active & (state.pending_rule >= 0)
    fire = jnp.where(armed, state.fire_at, INF)
    return jnp.minimum(
        fire.min(initial=jnp.inf),
        jnp.where(state.active, state.hb_due, INF).min(initial=jnp.inf),
    )


class TickKernel:
    """Compiled tick for one resource kind on one device (or data-sharded).

    Holds the rule table on device and a jitted, state-donating tick. The
    sharded multi-device variant lives in kwok_tpu.parallel.sharded_tick and
    reuses `tick_body`.
    """

    def __init__(
        self,
        table: CompiledRules,
        hb_interval: float = 30.0,
        hb_phases: tuple[str, ...] = (),
        hb_sel_bit: int = -1,
    ) -> None:
        self.table = table
        self.hb_interval = float(hb_interval)
        mask = 0
        for p in hb_phases:
            mask |= 1 << table.space.phase_id(p)
        self.hb_phase_mask = mask
        self.hb_sel_bit = int(hb_sel_bit)
        self._rules = _rule_arrays(table)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _tick(state: RowState, now: jnp.ndarray, key: jax.Array) -> TickOutputs:
            return tick_body(
                state, now, key, self._rules, self.hb_interval,
                self.hb_phase_mask, self.hb_sel_bit,
            )

        self._tick = _tick
        self._key = jax.random.PRNGKey(0)
        self._step = 0

    def __call__(self, state: RowState, now: float) -> TickOutputs:
        self._step += 1
        key = jax.random.fold_in(self._key, self._step)
        return self._tick(state, jnp.float32(now), key)


class MultiTickKernel:
    """One dispatch ticks SEVERAL resource kinds (nodes + pods).

    The reference pays one goroutine wake-up per object; the naive batched
    engine pays one device dispatch (and, on a tunneled/remote TPU, one
    round-trip) per resource kind per tick. Fusing the kinds into a single
    jitted call makes the whole engine step one XLA program — measured on
    the tunneled v5e chip, dispatch latency (~70 ms RTT) dominates the 1M-row
    compute (~4 ms), so this halves tick wall time; with async host fetches
    (see `prefetch`) ticks pipeline without blocking at all.

    specs: list of (table, hb_interval, hb_phases, hb_sel_bit) per kind.
    With `mesh`, every kind's rows shard over the mesh like ShardedTickKernel
    (counters psum'd over ICI).

    With pack=True, __call__ returns (outputs, wire) where wire is the
    tick's whole host-visible summary in ONE uint8 device array: 4*2K bytes
    of int32 counters (transitions per kind, then heartbeats per kind),
    followed by all dirty/deleted/hb masks bit-packed (8x fewer bytes, one
    transfer instead of 2+3K — D2H latency is per-array on remote devices).
    Split with `unpack_wire`.

    With pack_rows=True (implies pack), the wire additionally carries every
    kind's post-tick phase (uint8) and cond_bits (uint32) arrays. That makes
    the wire SELF-CONTAINED: the host can update its phase/cond mirrors and
    emit patches for tick N without ever touching N's output state — which
    the donate_argnums dispatch of tick N+1 has already invalidated. This is
    what lets the engine keep several ticks in flight (pipelined tick loop)
    instead of blocking a full device round-trip per tick. Cost: 5 bytes/row
    /kind/tick of extra D2H — negligible at engine populations; benches that
    only need counters+masks keep pack_rows=False.

    With steps>1, ONE dispatch advances `steps` inner ticks via lax.scan
    (simulated time advancing `dt` per step): counters sum over the steps
    and masks OR together, so a row that transitioned twice within one
    dispatch is patched once with its final state — the same coalescing the
    engine applies whenever multiple events land between emits. This
    divides both dispatch overhead and D2H bytes per simulated tick by
    `steps`, which is what a latency-heavy tunneled device needs.
    """

    def __init__(
        self, specs, mesh=None, pack: bool = False,
        steps: int = 1, dt: float = 0.0, pack_rows: bool = False,
    ) -> None:
        self._metas = []
        for table, hb_interval, hb_phases, hb_sel_bit in specs:
            mask = 0
            for p in hb_phases:
                mask |= 1 << table.space.phase_id(p)
            self._metas.append(
                (_rule_arrays(table), float(hb_interval), mask, int(hb_sel_bit))
            )
        self.mesh = mesh
        n = len(self._metas)

        if mesh is None:

            def _step(states, now, keys):
                return tuple(
                    tick_body(s, now, k, rules, hb, hm, hs)
                    for s, k, (rules, hb, hm, hs) in zip(states, keys, self._metas)
                )

        else:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            from kwok_tpu.parallel.mesh import ROWS_AXIS

            state_spec = RowState(*([P(ROWS_AXIS)] * len(RowState._fields)))
            out_spec = TickOutputs(
                state=state_spec,
                dirty=P(ROWS_AXIS),
                deleted=P(ROWS_AXIS),
                hb_fired=P(ROWS_AXIS),
                transitions=P(),
                heartbeats=P(),
            )

            def _one(rules, hb, hm, hs):
                def fn(state, now, key):
                    idx = jax.lax.axis_index(ROWS_AXIS)
                    out = tick_body(
                        state, now, jax.random.fold_in(key, idx), rules, hb, hm, hs
                    )
                    return out._replace(
                        transitions=jax.lax.psum(out.transitions, ROWS_AXIS),
                        heartbeats=jax.lax.psum(out.heartbeats, ROWS_AXIS),
                    )

                return shard_map(
                    fn, mesh=mesh, in_specs=(state_spec, P(), P()), out_specs=out_spec
                )

            shards = [_one(*meta) for meta in self._metas]

            def _step(states, now, keys):
                return tuple(
                    sh(s, now, k) for sh, s, k in zip(shards, states, keys)
                )

        self.steps = int(steps)
        self.dt = float(dt)
        if self.steps > 1:
            base_step = _step
            n_steps = self.steps
            dt_f = jnp.float32(self.dt)

            def _step(states, now, keys):  # noqa: F811
                def body(carry, i):
                    sts, acc = carry
                    step_keys = tuple(jax.random.fold_in(k, i) for k in keys)
                    outs = base_step(sts, now + i.astype(jnp.float32) * dt_f,
                                     step_keys)
                    new_sts = tuple(o.state for o in outs)
                    new_acc = tuple(
                        (a[0] | o.dirty, a[1] | o.deleted, a[2] | o.hb_fired,
                         a[3] + o.transitions, a[4] + o.heartbeats)
                        for a, o in zip(acc, outs)
                    )
                    return (new_sts, new_acc), None

                acc0 = tuple(
                    (jnp.zeros_like(s.active), jnp.zeros_like(s.active),
                     jnp.zeros_like(s.active), jnp.int32(0), jnp.int32(0))
                    for s in states
                )
                (sts, acc), _ = jax.lax.scan(
                    body, (tuple(states), acc0), jnp.arange(n_steps)
                )
                return tuple(
                    TickOutputs(
                        state=s, dirty=a[0], deleted=a[1], hb_fired=a[2],
                        transitions=a[3], heartbeats=a[4],
                    )
                    for s, a in zip(sts, acc)
                )

        self.pack_rows = bool(pack_rows)
        self.pack = bool(pack) or self.pack_rows
        if self.pack:
            inner = _step
            with_rows = self.pack_rows

            def _step(states, now, keys):  # noqa: F811
                outs = inner(states, now, keys)
                counters = jnp.stack(
                    [o.transitions for o in outs] + [o.heartbeats for o in outs]
                ).astype(jnp.int32)
                counter_bytes = jax.lax.bitcast_convert_type(
                    counters, jnp.uint8
                ).reshape(-1)
                dues = jnp.stack([next_due(o.state) for o in outs])
                due_bytes = jax.lax.bitcast_convert_type(
                    dues.astype(jnp.float32), jnp.uint8
                ).reshape(-1)
                bits = [
                    jnp.packbits(
                        jnp.stack([o.dirty, o.deleted, o.hb_fired]).reshape(-1)
                    )
                    for o in outs
                ]
                rows = []
                if with_rows:
                    for o in outs:
                        rows.append(o.state.phase.astype(jnp.uint8))
                        rows.append(
                            jax.lax.bitcast_convert_type(
                                o.state.cond_bits, jnp.uint8
                            ).reshape(-1)
                        )
                return outs, jnp.concatenate(
                    [counter_bytes, due_bytes] + bits + rows
                )

        self._tick = jax.jit(_step, donate_argnums=(0,))
        self._key = jax.random.PRNGKey(0)
        self._step_n = 0
        self._n = n

    def place(self, state: RowState) -> RowState:
        if self.mesh is None:
            return to_device(state)
        from kwok_tpu.parallel.mesh import row_sharding

        sh = row_sharding(self.mesh)
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), state)

    def __call__(self, states, now: float):
        self._step_n += 1
        base = jax.random.fold_in(self._key, self._step_n)
        keys = tuple(jax.random.fold_in(base, i) for i in range(self._n))
        return self._tick(tuple(states), jnp.float32(now), keys)


def unpack_wire(
    blob: np.ndarray, capacities: list[int], lazy: bool = True,
    rows: bool = False,
):
    """Invert the pack=True wire blob.

    Returns (counters, masks_fn, next_dues): counters is int32[2K]
    (transitions per kind then heartbeats per kind); next_dues is f32[K]
    (earliest pending timer per kind, +inf = nothing scheduled — the tick
    loop sleeps until then); masks_fn() materializes, per kind, (dirty,
    deleted, hb_fired) boolean arrays — deferred so quiet ticks never pay
    the unpack.

    With rows=True (a pack_rows=True blob), returns a 4th element rows_fn:
    rows_fn() materializes, per kind, (phase uint8[cap], cond uint32[cap])
    — the post-tick mirror values, so the caller never needs the (already
    donated) output state."""
    n = len(capacities)
    counters = blob[: 8 * n].view(np.int32)
    next_dues = blob[8 * n : 12 * n].view(np.float32)
    mask_end = 12 * n + sum((3 * cap + 7) // 8 for cap in capacities)

    def masks_fn():
        out = []
        off = 12 * n
        for cap in capacities:
            seg_bytes = (3 * cap + 7) // 8
            seg = np.unpackbits(blob[off : off + seg_bytes], count=3 * cap)
            m = seg.reshape(3, cap).astype(bool)
            out.append((m[0], m[1], m[2]))
            off += seg_bytes
        return out

    if not rows:
        return counters, (masks_fn if lazy else masks_fn()), next_dues

    def rows_fn():
        out = []
        off = mask_end
        for cap in capacities:
            phase = blob[off : off + cap]
            off += cap
            # copy before the u32 view: the slice's byte offset is not
            # 4-aligned in general and numpy rejects misaligned views
            cond = blob[off : off + 4 * cap].copy().view(np.uint32)
            off += 4 * cap
            out.append((phase, cond))
        return out

    return counters, (masks_fn if lazy else masks_fn()), next_dues, rows_fn


def lane_views(masks, rows, n_lanes: int, r: int):
    """Per-shard index slices of an unpacked STACKED wire.

    The sharded host pipeline (engine/lanes.py) keeps every lane's rows in
    one stacked device state: lane ``i`` owns rows ``[i*r, (i+1)*r)``. This
    carves the unpacked wire into exactly those slices so the coordinator
    can hand each lane its own view without copying: for each lane, a list
    of per-kind ``(dirty, deleted, hb, phase, cond)`` tuples. ``masks`` is
    ``masks_fn()``'s output, ``rows`` is ``rows_fn()``'s (or None — the
    phase/cond entries come back None then, e.g. a heartbeat-only wire).

    The slices are numpy VIEWS over the freshly materialized wire arrays —
    lanes own disjoint ranges, so one lane clearing stale mask bits in its
    slice can never touch another lane's rows.
    """
    out = []
    for lane in range(n_lanes):
        lo, hi = lane * r, (lane + 1) * r
        kinds = []
        for ki, (dirty, deleted, hb) in enumerate(masks):
            if rows is not None:
                ph, cb = rows[ki]
                ph, cb = ph[lo:hi], cb[lo:hi]
            else:
                ph = cb = None
            kinds.append((dirty[lo:hi], deleted[lo:hi], hb[lo:hi], ph, cb))
        out.append(kinds)
    return out


def gather_deadlines(state: RowState):
    """Host copies of the device-owned timer fields ``(fire_at, hb_due,
    gen)`` — the checkpoint gather (resilience/checkpoint.py). The async
    copies are started together so the three D2H transfers overlap; the
    np.asarray consumption then blocks once. Runs on the device-owning
    loop between dispatches, where the state arrays are live outputs
    (not yet donated to the next dispatch)."""
    prefetch((state.fire_at, state.hb_due, state.gen))
    return (
        np.asarray(state.fire_at),
        np.asarray(state.hb_due),
        np.asarray(state.gen),
    )


def prefetch(tree) -> None:
    """Start async device->host copies for every array in `tree`.

    Consuming np.asarray(...) later then costs ~0: the transfer overlapped
    with whatever the host did in between (next tick dispatch, patch
    rendering). No-op for arrays that don't support async copy (numpy)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        fn = getattr(leaf, "copy_to_host_async", None)
        if fn is not None:
            fn()


def to_device(state: RowState) -> RowState:
    return jax.tree_util.tree_map(jnp.asarray, state)


def to_host(out: Any) -> Any:
    """Copy a pytree of device arrays to mutable host numpy arrays."""
    return jax.tree_util.tree_map(lambda a: np.array(a), out)
