"""The jitted tick kernel.

One call advances an entire resource population one step:

  1. (re)match: every active row is matched against the compiled rule table
     (first match wins). A row whose best rule CHANGED since last tick —
     because ingest updated its phase / deletionTimestamp / selector bits —
     is re-armed with a freshly sampled delay. This replaces the reference's
     event-driven channels (watch event -> chan -> worker,
     pkg/kwok/controllers/node_controller.go:301-354,
     pod_controller.go:205-250): ingest only writes row fields; the next tick
     notices.
  2. fire: rows whose pending rule's fire-time has arrived transition: phase
     and condition bits update, generation bumps, and the row lands in the
     dirty mask (status patch needed) or deleted mask (API delete needed,
     the analogue of pod_controller.go:155-183).
  3. heartbeat: a vectorized timer wheel replaces KeepNodeHeartbeat's
     snapshot-sort-fanout over a 16-worker pool
     (node_controller.go:175-204): rows in heartbeat-enabled phases with
     hb_due <= now land in the hb_fired mask and get hb_due += interval.

Everything is branch-free jnp; the whole function jits to one XLA program.
Matching broadcasts a [C, R] boolean — R (rule count) is tiny (<32), so this
stays bandwidth-bound on the row arrays, which is the right regime for TPU.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kwok_tpu.models.compiler import CompiledRules
from kwok_tpu.models.lifecycle import DelayKind
from kwok_tpu.ops.state import RowState, TickOutputs

INF = jnp.float32(jnp.inf)


def _rule_arrays(table: CompiledRules) -> dict[str, jnp.ndarray]:
    return {
        "from_mask": jnp.asarray(table.from_mask, jnp.uint32),
        "deletion": jnp.asarray(table.deletion, jnp.int8),
        "selector_bit": jnp.asarray(table.selector_bit, jnp.int32),
        "delay_kind": jnp.asarray(table.delay_kind, jnp.int8),
        "delay_a": jnp.asarray(table.delay_a, jnp.float32),
        "delay_b": jnp.asarray(table.delay_b, jnp.float32),
        "to_phase": jnp.asarray(table.to_phase, jnp.int32),
        "cond_assign": jnp.asarray(table.cond_assign, jnp.uint32),
        "cond_value": jnp.asarray(table.cond_value, jnp.uint32),
        "is_delete": jnp.asarray(table.is_delete, bool),
    }


def tick_body(
    state: RowState,
    now: jnp.ndarray,
    key: jax.Array,
    rules: dict[str, jnp.ndarray],
    hb_interval: float,
    hb_phase_mask: int,
    hb_sel_bit: int = -1,
) -> TickOutputs:
    """Pure tick function — shared by the single-device jit and shard_map."""
    capacity = state.active.shape[0]
    num_rules = rules["from_mask"].shape[0]

    active = state.active
    phase = state.phase

    if num_rules > 0:
        # --- 1. match ------------------------------------------------------
        phase_u = phase.astype(jnp.uint32)
        phase_ok = ((rules["from_mask"][None, :] >> phase_u[:, None]) & 1) == 1
        deletion = rules["deletion"][None, :].astype(jnp.int32)
        del_ok = (deletion == -1) | (
            (deletion == 1) == state.has_deletion[:, None]
        )
        sel_bit = rules["selector_bit"][None, :]
        sel_ok = (sel_bit < 0) | (
            ((state.sel_bits[:, None] >> jnp.maximum(sel_bit, 0).astype(jnp.uint32)) & 1) == 1
        )
        match = phase_ok & del_ok & sel_ok  # [C, R]
        any_match = match.any(axis=1)
        first = jnp.argmax(match, axis=1).astype(jnp.int32)  # first True
        best = jnp.where(active & any_match, first, jnp.int32(-1))

        # Re-arm rows whose best rule changed (covers newly matched rows and
        # rows invalidated by ingest writes).
        rearm = active & (best != state.pending_rule) & (best >= 0)
        rid = jnp.maximum(best, 0)
        dk = rules["delay_kind"][rid].astype(jnp.int32)
        a = rules["delay_a"][rid]
        b = rules["delay_b"][rid]
        u = jax.random.uniform(
            key, (capacity,), jnp.float32, minval=1e-7, maxval=1.0
        )
        d_uniform = a + (b - a) * u
        d_exp = -a * jnp.log(u)
        d_exp = jnp.where(b > 0, jnp.minimum(d_exp, b), d_exp)
        delay = jnp.where(
            dk == DelayKind.CONSTANT,
            a,
            jnp.where(dk == DelayKind.UNIFORM, d_uniform, d_exp),
        )
        pending = jnp.where(active, best, jnp.int32(-1))
        fire_at = jnp.where(
            rearm, now + delay, jnp.where(pending >= 0, state.fire_at, INF)
        )

        # --- 2. fire -------------------------------------------------------
        can_fire = active & (pending >= 0) & (now >= fire_at)
        frid = jnp.maximum(pending, 0)
        fired_delete = can_fire & rules["is_delete"][frid]
        new_phase = jnp.where(can_fire, rules["to_phase"][frid], phase)
        assign = rules["cond_assign"][frid]
        value = rules["cond_value"][frid]
        new_cond = jnp.where(
            can_fire, (state.cond_bits & ~assign) | value, state.cond_bits
        )
        pending = jnp.where(can_fire, jnp.int32(-1), pending)
        fire_at = jnp.where(can_fire, INF, fire_at)
        new_gen = state.gen + can_fire.astype(jnp.int32)
        dirty = can_fire & ~fired_delete
    else:
        new_phase = phase
        new_cond = state.cond_bits
        pending = state.pending_rule
        fire_at = state.fire_at
        new_gen = state.gen
        can_fire = jnp.zeros(capacity, bool)
        dirty = can_fire
        fired_delete = can_fire

    # --- 3. heartbeat wheel ------------------------------------------------
    # Gating: by phase set (hb_phase_mask; 0 = every phase) and/or by a
    # selector bit (hb_sel_bit; reference semantics: every node passing the
    # manage-selectors heartbeats, even disregarded ones —
    # node_controller.go:205-207 needHeartbeat vs needLockNode). Disabled
    # entirely when both are "match nothing" (mask 0 and bit -1).
    if hb_phase_mask == 0 and hb_sel_bit < 0:
        hb_on = jnp.zeros_like(active)
    else:
        hb_on = active
        if hb_phase_mask != 0:
            hb_mask = jnp.uint32(hb_phase_mask)
            hb_on = hb_on & (((hb_mask >> new_phase.astype(jnp.uint32)) & 1) == 1)
        if hb_sel_bit >= 0:
            hb_on = hb_on & (
                ((state.sel_bits >> jnp.uint32(hb_sel_bit)) & 1) == 1
            )
    entered = hb_on & jnp.isinf(state.hb_due)
    hb_fired = hb_on & (now >= state.hb_due)
    hb_due = jnp.where(
        ~hb_on,
        INF,
        jnp.where(hb_fired | entered, now + jnp.float32(hb_interval), state.hb_due),
    )

    new_state = RowState(
        active=active,
        phase=new_phase,
        cond_bits=new_cond,
        sel_bits=state.sel_bits,
        has_deletion=state.has_deletion,
        pending_rule=pending,
        fire_at=fire_at,
        hb_due=hb_due,
        gen=new_gen,
    )
    return TickOutputs(
        state=new_state,
        dirty=dirty,
        deleted=fired_delete,
        hb_fired=hb_fired,
        transitions=can_fire.sum(dtype=jnp.int32),
        heartbeats=hb_fired.sum(dtype=jnp.int32),
    )


class TickKernel:
    """Compiled tick for one resource kind on one device (or data-sharded).

    Holds the rule table on device and a jitted, state-donating tick. The
    sharded multi-device variant lives in kwok_tpu.parallel.sharded_tick and
    reuses `tick_body`.
    """

    def __init__(
        self,
        table: CompiledRules,
        hb_interval: float = 30.0,
        hb_phases: tuple[str, ...] = (),
        hb_sel_bit: int = -1,
    ) -> None:
        self.table = table
        self.hb_interval = float(hb_interval)
        mask = 0
        for p in hb_phases:
            mask |= 1 << table.space.phase_id(p)
        self.hb_phase_mask = mask
        self.hb_sel_bit = int(hb_sel_bit)
        self._rules = _rule_arrays(table)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _tick(state: RowState, now: jnp.ndarray, key: jax.Array) -> TickOutputs:
            return tick_body(
                state, now, key, self._rules, self.hb_interval,
                self.hb_phase_mask, self.hb_sel_bit,
            )

        self._tick = _tick
        self._key = jax.random.PRNGKey(0)
        self._step = 0

    def __call__(self, state: RowState, now: float) -> TickOutputs:
        self._step += 1
        key = jax.random.fold_in(self._key, self._step)
        return self._tick(state, jnp.float32(now), key)


def to_device(state: RowState) -> RowState:
    return jax.tree_util.tree_map(jnp.asarray, state)


def to_host(out: Any) -> Any:
    """Copy a pytree of device arrays to mutable host numpy arrays."""
    return jax.tree_util.tree_map(lambda a: np.array(a), out)
