"""Struct-of-arrays cluster state for one resource kind.

The reference keeps cluster state as Go objects spread across client-go
caches, channels, and mutexed string sets (pkg/kwok/controllers/utils.go:163-205).
Here a resource kind's rows live in fixed-capacity parallel arrays so the
whole population is one tensor program:

  active        bool[C]    row in use
  phase         int32[C]   phase id (kwok_tpu.models.lifecycle.PhaseSpace)
  cond_bits     uint32[C]  condition status bits
  sel_bits      uint32[C]  host-computed selector-match bits
  has_deletion  bool[C]    deletionTimestamp present
  pending_rule  int32[C]   matched-but-not-fired rule id, -1 if unmatched
  fire_at       f32[C]     engine-time the pending rule fires (+inf if none)
  hb_due        f32[C]     next heartbeat time (+inf = no heartbeat)
  gen           int32[C]   bumped on every transition (host patch dedup)

Times are float32 seconds since the engine epoch (wall-clock captured once at
startup); f32 keeps sub-10ms resolution for over a day of continuous run,
and the host converts back to RFC3339 at the API boundary.

Capacity is static (XLA wants static shapes); the host grows by doubling:
allocate a bigger state and copy (kwok_tpu.engine handles the row pool and
free-list — tombstoned rows are recycled, mirroring the reference's ipPool
Put/Get recycling, utils.go:52-117).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

INF = np.float32(np.inf)


class RowState(NamedTuple):
    """One resource kind's rows. A pytree of arrays (jnp or np)."""

    active: np.ndarray  # bool[C]
    phase: np.ndarray  # int32[C]
    cond_bits: np.ndarray  # uint32[C]
    sel_bits: np.ndarray  # uint32[C]
    has_deletion: np.ndarray  # bool[C]
    pending_rule: np.ndarray  # int32[C]
    fire_at: np.ndarray  # float32[C]
    hb_due: np.ndarray  # float32[C]
    gen: np.ndarray  # int32[C]

    @property
    def capacity(self) -> int:
        return int(self.active.shape[0])


class TickOutputs(NamedTuple):
    """What one tick hands back to the host."""

    state: RowState
    dirty: np.ndarray  # bool[C] — transitioned this tick: needs status patch
    deleted: np.ndarray  # bool[C] — fired a delete-effect rule: needs DELETE
    hb_fired: np.ndarray  # bool[C] — heartbeat due: needs heartbeat patch
    transitions: np.ndarray  # int32 scalar — transitions this tick
    heartbeats: np.ndarray  # int32 scalar — heartbeat firings this tick


def new_row_state(capacity: int, xp=np) -> RowState:
    """Fresh empty state. `xp` may be numpy or jax.numpy."""
    return RowState(
        active=xp.zeros(capacity, bool),
        phase=xp.zeros(capacity, np.int32),
        cond_bits=xp.zeros(capacity, np.uint32),
        sel_bits=xp.zeros(capacity, np.uint32),
        has_deletion=xp.zeros(capacity, bool),
        pending_rule=xp.full(capacity, -1, np.int32),
        fire_at=xp.full(capacity, INF, np.float32),
        hb_due=xp.full(capacity, INF, np.float32),
        gen=xp.zeros(capacity, np.int32),
    )


def grow(state: RowState, new_capacity: int) -> RowState:
    """Host-side capacity doubling (numpy arrays only)."""
    old = state.capacity
    if new_capacity <= old:
        return state
    out = new_row_state(new_capacity, np)
    for name in RowState._fields:
        getattr(out, name)[:old] = np.asarray(getattr(state, name))
    return out
